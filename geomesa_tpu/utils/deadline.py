"""Per-query deadlines: contextvar-propagated cooperative cancellation.

The reference bounds every query with ``geomesa.query.timeout`` enforced
by a reaper thread over live scan sessions (index/utils/ThreadManagement
.scala:21-60, plus Accumulo's own scan-session eviction). This rebuild
has no reaper; instead the budget travels WITH the query as an ambient
``Deadline`` (a contextvars value, the same propagation the tracer uses)
and every boundary that can stall — each named fault point, each scanned
block, each socket — checks it cooperatively:

* ``deadline.check(point)`` raises ``QueryTimeout`` the moment the
  budget is gone, so a latency-fault schedule costs at most the deadline
  plus one fault-point granularity (the "bounded latency" half of the
  parity-under-faults invariant, ROADMAP.md).
* ``deadline.io_timeout(default)`` derives a socket timeout from the
  remaining budget, so no blocking recv can outlive its query
  (stream/netlog.py, tools/enrichment.py).
* ``utils.retry.RetryPolicy`` caps its per-call deadline and every
  backoff sleep at the ambient remaining budget, so a retry loop can
  never outlive the query that started it.

With no deadline installed (the common case) every helper is one
ContextVar read — cheap enough to sit on per-block scan paths, the same
free-when-off posture as ``trace.span`` and ``faults.fault_point``.
Timed-out work fails CRISPLY: callers get ``QueryTimeout``, never a
truncated result set. Exceeded budgets are counted in
``utils.audit.robustness_metrics()`` under ``deadline.exceeded`` and
land on the suffering query's trace as a ``deadline.exceeded`` event.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Optional

from geomesa_tpu.utils import trace
from geomesa_tpu.utils.audit import QueryTimeout, robustness_metrics

_CURRENT: contextvars.ContextVar[Optional["Deadline"]] = contextvars.ContextVar(
    "geomesa_tpu_deadline", default=None
)


class Deadline:
    """One query's time budget: an absolute monotonic expiry plus the
    original budget (for error messages / telemetry)."""

    __slots__ = ("budget_s", "t_end")

    def __init__(self, budget_s: float, t_end: Optional[float] = None):
        self.budget_s = float(budget_s)
        self.t_end = (
            time.monotonic() + self.budget_s if t_end is None else float(t_end)
        )

    def remaining(self) -> float:
        """Seconds of budget left (negative once expired)."""
        return self.t_end - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, point: str = "") -> None:
        """Raise ``QueryTimeout`` if the budget is exhausted. ``point``
        names the boundary that noticed (fault-point names, "scan.block",
        "admit.wait", ...) — it lands in the exception, the counter's
        trace event, and therefore the slow-query log."""
        if self.t_end - time.monotonic() > 0.0:
            return
        robustness_metrics().inc("deadline.exceeded")
        # the timeout attributes to the suffering query's own span tree,
        # next to whatever fault/latency event ate the budget
        trace.event("deadline.exceeded", point=point, budget_s=self.budget_s)
        where = f" at {point}" if point else ""
        raise QueryTimeout(
            f"query exceeded its {self.budget_s:g}s budget{where} "
            "(geomesa.query.timeout analog)"
        )


@contextmanager
def budget(budget_s: Optional[float]):
    """Activate a deadline for the calling scope::

        with deadline.budget(store.query_timeout_s):
            ...  # every check()/io_timeout() below sees it

    ``None`` is a no-op passthrough (yields the ambient deadline, if
    any). A nested budget can only TIGHTEN: when an outer deadline
    expires sooner, the inner scope inherits the outer expiry — a
    sub-operation's own allowance never extends its query's budget."""
    if budget_s is None:
        yield _CURRENT.get()
        return
    d = Deadline(budget_s)
    outer = _CURRENT.get()
    if outer is not None and outer.t_end < d.t_end:
        d = Deadline(budget_s, t_end=outer.t_end)
    token = _CURRENT.set(d)
    try:
        yield d
    finally:
        _CURRENT.reset(token)


def ambient() -> Optional[Deadline]:
    """The calling context's deadline, or None when unbounded."""
    return _CURRENT.get()


def check(point: str = "") -> None:
    """Cooperative cancellation hook: ``QueryTimeout`` when the ambient
    budget is exhausted, free no-op otherwise. Sits next to every named
    ``faults.fault_point`` (enforced by scripts/lint_robustness.sh)."""
    d = _CURRENT.get()
    if d is not None:
        d.check(point)


def remaining() -> Optional[float]:
    """Ambient remaining budget in seconds, or None when unbounded."""
    d = _CURRENT.get()
    return None if d is None else d.remaining()


def io_timeout(default_s: Optional[float], point: str = "io") -> Optional[float]:
    """A socket/IO timeout derived from the remaining budget:
    ``min(default_s, remaining)``, or ``default_s`` when unbounded.
    Raises ``QueryTimeout`` (rather than returning a zero timeout) when
    the budget is already gone — the I/O must not start at all."""
    d = _CURRENT.get()
    if d is None:
        return default_s
    d.check(point)
    left = d.remaining()
    return left if default_s is None else min(float(default_s), left)
