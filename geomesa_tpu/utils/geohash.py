"""Geohash encode/decode/neighbors, vectorized.

Reference: geomesa-utils geohash/GeoHash.scala:1-414 + GeohashUtils.scala
(used by the KNN spiral and legacy indices). Base-32 alphabet, interleaved
lon/lat bits, msb-first — interoperable with the standard geohash system.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_DECODE = {c: i for i, c in enumerate(_BASE32)}


def encode(lon, lat, precision: int = 9) -> np.ndarray:
    """Geohash strings of ``precision`` chars; vectorized over arrays."""
    lon = np.atleast_1d(np.asarray(lon, dtype=np.float64))
    lat = np.atleast_1d(np.asarray(lat, dtype=np.float64))
    nbits = precision * 5
    lon_bits = (nbits + 1) // 2
    lat_bits = nbits // 2
    xi = np.minimum(
        ((lon + 180.0) / 360.0 * (1 << lon_bits)).astype(np.uint64),
        (1 << lon_bits) - 1,
    )
    yi = np.minimum(
        ((lat + 90.0) / 180.0 * (1 << lat_bits)).astype(np.uint64),
        (1 << lat_bits) - 1,
    )
    # interleave msb-first: even global bit positions (0,2,..) are lon
    z = np.zeros(len(xi), dtype=np.uint64)
    for b in range(nbits):
        if b % 2 == 0:  # lon bit, msb first
            src = xi >> np.uint64(lon_bits - 1 - b // 2)
        else:
            src = yi >> np.uint64(lat_bits - 1 - b // 2)
        z = (z << np.uint64(1)) | (src & np.uint64(1))
    out = np.empty(len(z), dtype=object)
    for i, v in enumerate(z):
        v = int(v)
        chars = []
        for c in range(precision):
            shift = 5 * (precision - 1 - c)
            chars.append(_BASE32[(v >> shift) & 0x1F])
        out[i] = "".join(chars)
    return out


def decode_bounds(geohash: str) -> Tuple[float, float, float, float]:
    """(xmin, ymin, xmax, ymax) of the geohash cell."""
    lon = [-180.0, 180.0]
    lat = [-90.0, 90.0]
    even = True
    for ch in geohash:
        cd = _DECODE[ch]
        for b in (16, 8, 4, 2, 1):
            rng = lon if even else lat
            mid = (rng[0] + rng[1]) / 2
            if cd & b:
                rng[0] = mid
            else:
                rng[1] = mid
            even = not even
    return (lon[0], lat[0], lon[1], lat[1])


def decode(geohash: str) -> Tuple[float, float]:
    """Cell-center (lon, lat)."""
    xmin, ymin, xmax, ymax = decode_bounds(geohash)
    return ((xmin + xmax) / 2, (ymin + ymax) / 2)


def decompose(geom, max_hashes: int = 32, max_precision: int = 6) -> List[str]:
    """Cover a geometry with geohash cells at mixed precisions.

    The GeohashUtils.decomposeGeometry analog (geomesa-utils
    GeohashUtils.scala): BFS refinement — a cell fully inside the geometry
    is emitted as-is, a boundary cell splits into its 32 children until the
    budget or precision cap is reached (remaining boundary cells are then
    emitted coarse, keeping the cover a SUPERSET of the geometry).
    """
    from geomesa_tpu.geom.base import Envelope, Polygon
    from geomesa_tpu.geom.predicates import geometries_intersect, geometry_within

    def cell_poly(gh: str) -> Polygon:
        xmin, ymin, xmax, ymax = decode_bounds(gh)
        return Polygon(
            [[xmin, ymin], [xmax, ymin], [xmax, ymax], [xmin, ymax], [xmin, ymin]]
        )

    env = geom.envelope
    # seed precision: grow until a single cell no longer contains the bbox
    seeds = [""]
    for p in range(1, max_precision + 1):
        gh = encode(
            np.asarray([(env.xmin + env.xmax) / 2]),
            np.asarray([(env.ymin + env.ymax) / 2]),
            p,
        )[0]
        xmin, ymin, xmax, ymax = decode_bounds(gh)
        if xmin <= env.xmin and xmax >= env.xmax and ymin <= env.ymin and ymax >= env.ymax:
            seeds = [gh]
        else:
            break

    out: List[str] = []
    frontier: List[str] = []
    for s in seeds:
        if s == "":
            # whole world: 32 top-level cells
            frontier.extend(_BASE32)
        else:
            frontier.append(s)
    while frontier:
        gh = frontier.pop(0)
        cp = cell_poly(gh)
        if not geometries_intersect(cp, geom):
            continue
        if geometry_within(cp, geom):
            out.append(gh)
        elif len(gh) >= max_precision or len(out) + len(frontier) >= max_hashes:
            out.append(gh)  # boundary cell at budget: keep coarse (superset)
        else:
            frontier.extend(gh + c for c in _BASE32)
    return sorted(out)


def neighbors(geohash: str) -> List[str]:
    """The 8 surrounding cells (grid walk via re-encode of offset centers)."""
    xmin, ymin, xmax, ymax = decode_bounds(geohash)
    w = xmax - xmin
    h = ymax - ymin
    cx = (xmin + xmax) / 2
    cy = (ymin + ymax) / 2
    out = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            x = cx + dx * w
            y = cy + dy * h
            if x < -180.0:
                x += 360.0
            elif x > 180.0:
                x -= 360.0
            if -90.0 <= y <= 90.0:
                out.append(str(encode(x, y, len(geohash))[0]))
    return out
