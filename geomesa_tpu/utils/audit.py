"""Query auditing + metrics + profiling.

Reference: audit interfaces (geomesa-utils audit/AuditedEvent.scala:1-102,
QueryEvent index/audit/QueryEvent.scala, async writers in
geomesa-accumulo audit/), Dropwizard metrics (geomesa-metrics
MetricsConfig.scala:26) and MethodProfiling/Timings
(utils/stats/MethodProfiling.scala:1-222). Kept deliberately lean: an event
dataclass, pluggable writers, and a counter/timer registry.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class QueryEvent:
    """One audited query (user, filter, timings, hits)."""

    store: str
    type_name: str
    user: str
    filter: str
    hints: Dict[str, Any]
    date_ms: int
    planning_ms: float
    scanning_ms: float
    hits: int
    # which execution path answered (host-seek / device-exact /
    # device-batch-dual / ... ; "+"-joined for union plans) — the extra
    # the reference's QueryEvent lacks but cost-gated execution needs
    scan_path: str = ""
    # trace correlation: the id of the span tree this query produced
    # (utils/trace.py), "" when the query ran untraced — audit rows and
    # /debug/traces join on it
    trace_id: str = ""
    # device cost receipt (utils/devstats.receipt_since): what THIS
    # query cost below the host — XLA compiles it triggered, bytes it
    # moved across the device link each way, and the padding efficiency
    # of any segment THIS query uploaded (0.0 when it uploaded none).
    # Upper bounds under concurrent streams (the counters are
    # process-wide), exact single-stream.
    recompiles: int = 0
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    pad_ratio: float = 0.0
    # how the query ended: "ok", "timeout" (QueryTimeout — budget
    # exhausted), or "shed" (ShedLoad — admission control refused it).
    # Timed-out and shed queries still audit: overload behavior must be
    # visible in the same trail as the successes it protected.
    outcome: str = "ok"


class AuditWriter:
    def write_event(self, event: QueryEvent) -> None:
        raise NotImplementedError


class InMemoryAuditWriter(AuditWriter):
    """Test/embedded sink; bounded ring of recent events."""

    def __init__(self, capacity: int = 10_000):
        self.capacity = capacity
        self.events: List[QueryEvent] = []
        self._lock = threading.Lock()

    def write_event(self, event: QueryEvent) -> None:
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.capacity:
                del self.events[: len(self.events) - self.capacity]


class LoggingAuditWriter(AuditWriter):
    def __init__(self, logger: Optional[logging.Logger] = None):
        self.logger = logger or logging.getLogger("geomesa_tpu.audit")

    def write_event(self, event: QueryEvent) -> None:
        self.logger.info(
            "query type=%s user=%s filter=%r plan=%.1fms scan=%.1fms hits=%d",
            event.type_name,
            event.user,
            event.filter,
            event.planning_ms,
            event.scanning_ms,
            event.hits,
        )


def histogram_summary(vals: List[float], total_count: Optional[int] = None) -> Dict[str, Any]:
    """Percentile summary of raw timer samples (seconds) -> ms leaves.

    Nearest-rank percentiles over the sorted reservoir: p50 keeps the
    historical ``arr[n // 2]`` (int(0.5 * n) == n // 2), and the tail
    quantiles (p90/p95/p99) are what latency budgets are written
    against — a mean/max pair hides exactly the stalls a per-stage
    tracer is meant to attribute. ``total_count`` is the CUMULATIVE
    update count (the reservoir is a sliding window; monotone consumers
    like Prometheus rate() must see the true total)."""
    arr = sorted(vals)
    n = len(arr)

    def q(p: float) -> float:
        return arr[min(n - 1, int(p * n))]

    return {
        "count": n if total_count is None else total_count,
        "mean_ms": 1000 * sum(arr) / n,
        "p50_ms": 1000 * q(0.50),
        "p90_ms": 1000 * q(0.90),
        "p95_ms": 1000 * q(0.95),
        "p99_ms": 1000 * q(0.99),
        "max_ms": 1000 * arr[-1],
    }


# -- timer exemplars ----------------------------------------------------------
#
# When the SLO engine is active (utils/slo.py), timer reservoirs also
# keep EXEMPLARS: (seconds, trace_id, wall_ms) triples filed per
# power-of-two latency bucket, plus a small recent ring — so /debug/slo
# and the Prometheus exposition can link a p99 straight to a retained
# trace in /debug/traces instead of leaving the operator to guess which
# query the percentile describes. The hook is flag-gated at module
# level: with the flag off (the default until a timeline sampler with
# exemplars starts), update_timer's added cost is ONE global read — the
# trace.span / fault_point free-when-off discipline, asserted by
# tests/test_timeline.py.

_EXEMPLARS = False
_EXEMPLAR_RECENT = 32  # recent-exemplar ring per timer
# bucket i covers [2^i, 2^(i+1)) milliseconds, clamped to this range
_EXEMPLAR_BUCKET_MIN = -4  # 62.5 us
_EXEMPLAR_BUCKET_MAX = 17  # ~131 s


def set_exemplars(on: bool) -> None:
    """Flip the process-wide exemplar hook (utils/timeline.py manages
    this against the sampler refcount; tests flip it directly)."""
    global _EXEMPLARS
    _EXEMPLARS = bool(on)


def exemplars_enabled() -> bool:
    return _EXEMPLARS


def exemplar_bucket(seconds: float) -> int:
    """floor(log2(milliseconds)), clamped — the shared latency-bucket
    rule for exemplars AND the timeline's per-tick timer histograms, so
    an SLO threshold maps to the same bucket edge in both."""
    ms = seconds * 1000.0
    if ms <= 0.0:
        return _EXEMPLAR_BUCKET_MIN
    b = math.frexp(ms)[1] - 1  # 2**b <= ms < 2**(b+1)
    return max(_EXEMPLAR_BUCKET_MIN, min(_EXEMPLAR_BUCKET_MAX, b))


class MetricsRegistry:
    """Counters + gauges + timers with a snapshot report (Dropwizard
    registry role). Timers report percentile summaries
    (histogram_summary); gauges are either set values or zero-arg
    callables sampled at snapshot time."""

    _RESERVOIR = 4096  # bounded per-timer samples (ring, like the audit sink)

    def __init__(self):
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, List[float]] = {}
        # cumulative (count, sum_s) per timer: the reservoir above is a
        # sliding window, but monotone consumers (Prometheus _count/_sum,
        # rate() dashboards) need totals that never move backwards
        self._timer_totals: Dict[str, List[float]] = {}
        self._gauges: Dict[str, float] = {}
        self._gauge_fns: Dict[str, Any] = {}
        # timer -> {"buckets": {bucket: (s, trace_id, wall_ms)},
        #           "recent": deque} — populated ONLY while the exemplar
        # flag is up (bounded: 22 buckets + a 32-deep ring per timer)
        self._exemplars: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_fn(self, name: str, fn) -> None:
        """Register a zero-arg callable sampled on every snapshot (cache
        sizes, queue depths — state that is cheaper to read than to
        maintain incrementally)."""
        with self._lock:
            self._gauge_fns[name] = fn

    def counter(self, name: str, default: int = 0) -> int:
        """One counter's current value — a single dict read under the
        lock, cheap enough for per-query receipt snapshots
        (utils/devstats.receipt_snapshot) on the hot path."""
        with self._lock:
            return int(self._counters.get(name, default))

    def gauge(self, name: str, default: float = 0.0) -> float:
        """One SET gauge's current value (gauge_fn callables are only
        sampled by snapshot() — this is the cheap point read)."""
        with self._lock:
            return float(self._gauges.get(name, default))

    def update_timer(self, name: str, seconds: float) -> None:
        ex: Optional[Tuple[float, str, float]] = None
        if _EXEMPLARS:
            # the trace-id read happens OUTSIDE the lock and ONLY under
            # the flag: disabled, this method's added cost is the one
            # module-global read above (the free-when-off contract)
            from geomesa_tpu.utils import trace as _trace

            ex = (
                float(seconds),
                _trace.current_trace_id() or "",
                time.time() * 1000.0,
            )
        with self._lock:
            vals = self._timers.setdefault(name, [])
            vals.append(seconds)
            if len(vals) > self._RESERVOIR:
                del vals[: len(vals) - self._RESERVOIR]
            tot = self._timer_totals.setdefault(name, [0, 0.0])
            tot[0] += 1
            tot[1] += seconds
            if ex is not None:
                slot = self._exemplars.get(name)
                if slot is None:
                    slot = self._exemplars[name] = {
                        "buckets": {},
                        "recent": deque(maxlen=_EXEMPLAR_RECENT),
                    }
                slot["buckets"][exemplar_bucket(seconds)] = ex
                slot["recent"].append(ex)

    def exemplars(self, name: Optional[str] = None) -> Dict[str, Any]:
        """Copy of the exemplar state: ``{timer: {"buckets": {bucket:
        (seconds, trace_id, wall_ms)}, "recent": [...]}}`` (one timer's
        slot when ``name`` is given, ``{}`` when it has none). Buckets
        keep the LAST exemplar per power-of-two latency bucket — the
        highest occupied bucket is the worst recent sample, which is
        what the p99 wants linked."""
        with self._lock:
            items = (
                [(name, self._exemplars.get(name))]
                if name is not None
                else list(self._exemplars.items())
            )
            out = {
                n: {
                    "buckets": dict(slot["buckets"]),
                    "recent": list(slot["recent"]),
                }
                for n, slot in items
                if slot is not None
            }
        return out.get(name, {}) if name is not None else out

    def drop_timer(self, name: str) -> None:
        """Remove one timer's reservoir, totals, and exemplars — how the
        plan-fingerprint registry (utils/plans.py) keeps its per-
        fingerprint timers bounded by the same LRU that bounds the
        fingerprints themselves."""
        with self._lock:
            self._timers.pop(name, None)
            self._timer_totals.pop(name, None)
            self._exemplars.pop(name, None)

    def timer(self, name: str):
        registry = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.update_timer(name, time.perf_counter() - self.t0)

        return _Ctx()

    def snapshot(self):
        """(counters, gauges, {timer: raw samples}, {timer: (count, sum_s)})
        — every collection COPIED under the lock, so concurrent
        inc/update_timer during a report can never mutate what a reporter
        is iterating. Timer samples are the sliding reservoir (percentile
        material); the totals are cumulative. Gauge callables are sampled
        OUTSIDE the lock (a gauge that reads another registry must not
        deadlock); a failing gauge is skipped rather than failing the
        snapshot."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            gauge_fns = list(self._gauge_fns.items())
            timers = {name: list(vals) for name, vals in self._timers.items()}
            totals = {
                name: (int(c), float(s))
                for name, (c, s) in self._timer_totals.items()
            }
        for name, fn in gauge_fns:
            try:
                gauges[name] = float(fn())
            except Exception:  # noqa: BLE001 - telemetry must not raise
                logging.getLogger("geomesa_tpu.audit").exception(
                    "gauge %r failed", name
                )
        return counters, gauges, timers, totals

    def report(self) -> Dict[str, Any]:
        counters, gauges, timers, totals = self.snapshot()
        out: Dict[str, Any] = counters
        out.update(gauges)
        for name, vals in timers.items():
            if not vals:  # a registered-but-never-updated timer: no math on it
                continue
            out[name] = histogram_summary(
                vals, total_count=totals.get(name, (None,))[0]
            )
        return out


_ROBUSTNESS: Optional[MetricsRegistry] = None
_ROBUSTNESS_LOCK = threading.Lock()


def robustness_metrics() -> MetricsRegistry:
    """Process-wide counters for the fault/retry/degradation layer:

        fault.<point>.<kind>       injected faults fired (utils.faults)
        retry.<name>.retries       re-attempts a RetryPolicy absorbed
        retry.<name>.giveup        retries exhausted (error surfaced)
        quarantine.files           corrupt files renamed aside
        degrade.device_to_host     queries degraded to the host scan path
        degrade.mirror_rebuilds    device mirrors evicted for rebuild
        deadline.exceeded          query budgets exhausted (utils.deadline)
        shed.overflow              queries refused outright (queue full)
        shed.queue_timeout         queries whose budget died in the queue
        breaker.<name>.opens       circuits tripped open (utils.breaker)
        breaker.<name>.reopens     half-open probes that failed
        breaker.<name>.closes      successful probes (circuit healed)
        breaker.<name>.probes      half-open probe attempts
        breaker.<name>.short_circuit  calls refused while open
        breaker.<name>.state       gauge: 0 closed / 0.5 half-open / 1 open

    One shared registry rather than per-store: the layers that fault
    (block readers, the RPC client, the device executor) are constructed
    below the store facade and shared across stores. A store's own
    ``metrics`` registry still carries its query timings; chaos soaks and
    operators read this one for failure-path behavior."""
    global _ROBUSTNESS
    with _ROBUSTNESS_LOCK:
        if _ROBUSTNESS is None:
            _ROBUSTNESS = MetricsRegistry()
        return _ROBUSTNESS


def decision(point: str, reason: str, **attrs: Any) -> None:
    """Reason-coded adaptive-decision audit: the ONE helper every
    decline/degrade/fallback/hedge/reroute branch routes through
    (scripts/lint_observability.sh rule 5 pins the pairing), so "why did
    the system take the slow/safe path" is answerable from three joined
    surfaces at once:

    * a ``decision.<point>`` span event (``reason`` + attrs) on the
      query that suffered it — free outside a trace;
    * a ``decision.<point>.<reason>`` counter in
      ``robustness_metrics()`` — rates/deltas on /metrics and the
      timeline;
    * a tally on the current query's plan fingerprint
      (utils/plans.py) — one contextvar read when plan telemetry is
      off, so the hook is hot-path safe.

    ``reason`` must be a STABLE code (``boundary_dominates``,
    ``antipodal_radius``), never a formatted message — messages go in
    ``attrs`` where they stay out of counter names."""
    robustness_metrics().inc(f"decision.{point}.{reason}")
    from geomesa_tpu.utils import trace as _trace

    _trace.event(f"decision.{point}", reason=reason, **attrs)
    from geomesa_tpu.utils import plans as _plans

    _plans.note(point, reason)


def _flatten(snapshot):
    """[(dotted_name, value)] — THE snapshot traversal every reporter
    shares (timer dicts become 'name.leaf' rows, sorted)."""
    out = []
    for name, val in sorted(snapshot.items()):
        if isinstance(val, dict):
            out.extend((f"{name}.{k}", v) for k, v in sorted(val.items()))
        else:
            out.append((name, val))
    return out


class Reporter:
    """Scheduled metrics publication (Dropwizard ScheduledReporter role,
    metrics/config/MetricsConfig.scala:26-60): start() emits a registry
    snapshot every ``interval_s`` on a daemon thread; report_now() for
    synchronous flushes (tests, shutdown)."""

    def __init__(self, registry: MetricsRegistry, interval_s: float = 60.0):
        self.registry = registry
        self.interval_s = interval_s
        self._timer: Any = None
        self._stopped = False

    def emit(self, snapshot: Dict[str, Any]) -> None:
        raise NotImplementedError

    def report_now(self) -> None:
        self.emit(self.registry.report())

    def start(self) -> "Reporter":
        self._stopped = False

        def tick():
            if self._stopped:  # stop() raced an in-flight fire
                return
            try:
                self.report_now()
            except Exception:  # noqa: BLE001 - one bad emit must not kill the loop
                # an emit() that raises (sink down, disk full) used to
                # skip schedule() and silently end the periodic loop
                # forever; log and keep the cadence — the next interval
                # retries against a possibly-recovered sink
                logging.getLogger("geomesa_tpu.audit").exception(
                    "%s emit failed; reporting continues", type(self).__name__
                )
            schedule()

        def schedule():
            if self._stopped:
                return
            t = threading.Timer(self.interval_s, tick)
            t.daemon = True
            t.start()
            self._timer = t

        schedule()
        return self

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None


class ConsoleReporter(Reporter):
    """ConsoleReporter analog: human-readable snapshot to a stream."""

    def __init__(self, registry, interval_s: float = 60.0, stream=None):
        super().__init__(registry, interval_s)
        import sys

        self.stream = stream or sys.stderr

    def emit(self, snapshot):
        import json as _json

        self.stream.write(f"-- metrics {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} --\n")
        self.stream.write(_json.dumps(snapshot, indent=1, default=str) + "\n")
        self.stream.flush()


class LoggingReporter(Reporter):
    """Slf4jReporter analog: snapshot through the logging module."""

    def __init__(self, registry, interval_s: float = 60.0, logger_name: str = "geomesa.metrics"):
        super().__init__(registry, interval_s)
        import logging

        self.logger = logging.getLogger(logger_name)

    def emit(self, snapshot):
        self.logger.info("metrics %s", snapshot)


class DelimitedFileReporter(Reporter):
    """DelimitedFileReporter analog: appends timestamped rows, one metric
    per line (tab-separated), for offline aggregation."""

    def __init__(self, registry, path: str, interval_s: float = 60.0):
        super().__init__(registry, interval_s)
        self.path = path

    def emit(self, snapshot):
        now = int(time.time() * 1000)
        with open(self.path, "a") as fh:
            for name, v in _flatten(snapshot):
                fh.write(f"{now}\t{name}\t{v}\n")


class GraphiteReporter(Reporter):
    """Network reporter speaking the Graphite/Carbon plaintext protocol
    (metrics/config/MetricsConfig.scala:26,99-117's GraphiteReporter
    role): one ``<prefix>.<name> <value> <epoch-s>`` line per metric over
    a persistent TCP connection. Timer dicts flatten to dotted leaves
    (``name.count``, ``name.mean_ms``, ...). A broken connection is
    re-dialed once per emission; a still-unreachable carbon endpoint
    drops that snapshot (metrics are telemetry — they must never take
    the query path down with them)."""

    def __init__(self, registry, host: str, port: int = 2003,
                 prefix: str = "geomesa", interval_s: float = 60.0):
        from geomesa_tpu.utils.retry import RetryPolicy

        super().__init__(registry, interval_s)
        self.host = host
        self.port = port
        self.prefix = prefix.rstrip(".")
        self._sock: Any = None
        # one reconnect per emission, through the shared policy
        self._retry = RetryPolicy(
            name="graphite", max_attempts=2, base_s=0.05, cap_s=0.1,
            retryable=(OSError,),
        )

    def _lines(self, snapshot: Dict[str, Any], now_s: int):
        for name, v in _flatten(snapshot):
            base = f"{self.prefix}.{name}" if self.prefix else name
            yield f"{base} {float(v):g} {now_s}\n"

    def _connect(self):
        import socket

        from geomesa_tpu.utils.config import SOCKET_TIMEOUT

        if self._sock is None:
            # shared knob, not a hardcoded constant: no I/O boundary is
            # unbounded-by-default, and operators tune ONE property
            self._sock = socket.create_connection(
                (self.host, self.port),
                timeout=SOCKET_TIMEOUT.to_duration_s(10.0),
            )
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def stop(self) -> None:
        super().stop()
        self.close()

    def emit(self, snapshot):
        payload = "".join(self._lines(snapshot, int(time.time()))).encode()
        if not payload:
            return

        def _send():
            try:
                self._connect().sendall(payload)
            except OSError:
                self.close()  # next attempt/emission redials
                raise

        try:
            self._retry.call(_send)
        except OSError:
            pass  # carbon unreachable: drop this snapshot (next interval retries)


class GangliaReporter(Reporter):
    """Ganglia gmetric reporter (metrics/config/MetricsConfig.scala:26's
    GangliaReporter role): one XDR metadata + value packet pair per
    metric over UDP, speaking the gmond 3.1 wire format. Timer dicts
    flatten to dotted leaves like the graphite edition. UDP is
    fire-and-forget — an absent gmond costs nothing and loses nothing
    but telemetry."""

    def __init__(self, registry, host: str, port: int = 8649,
                 group: str = "geomesa", interval_s: float = 60.0):
        super().__init__(registry, interval_s)
        self.host = host
        self.port = port
        self.group = group

    @staticmethod
    def _xdr_str(s: str) -> bytes:
        import struct

        b = s.encode()
        return struct.pack("!I", len(b)) + b + b"\0" * (-len(b) % 4)

    def _packets(self, name: str, value: float):
        """(metadata, value) XDR packet pair for one double metric."""
        import struct

        xs = self._xdr_str
        hostname = "geomesa-tpu"
        # metadata packet: id 128 — host, name, spoof=0, type, name,
        # units, slope BOTH(3), tmax 60, dmax 0, extra {GROUP: group}
        meta = (
            struct.pack("!I", 128)
            + xs(hostname) + xs(name) + struct.pack("!I", 0)
            + xs("double") + xs(name) + xs("")
            + struct.pack("!III", 3, max(60, int(self.interval_s)), 0)
            + struct.pack("!I", 1) + xs("GROUP") + xs(self.group)
        )
        # value packet: id 133 (string-formatted value) — host, name,
        # spoof=0, printf format, value
        val = (
            struct.pack("!I", 133)
            + xs(hostname) + xs(name) + struct.pack("!I", 0)
            + xs("%s") + xs(f"{float(value):g}")
        )
        return meta, val

    def emit(self, snapshot):
        import socket

        flat = _flatten(snapshot)
        if not flat:
            return
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            for name, value in flat:
                for pkt in self._packets(name, value):
                    try:
                        sock.sendto(pkt, (self.host, self.port))
                    except OSError:
                        return  # unreachable gmond: drop the snapshot
        finally:
            sock.close()


def _prom_name(name: str, prefix: str = "geomesa") -> str:
    """Metric name -> Prometheus-legal name: dotted segments join with
    underscores, anything outside [a-zA-Z0-9_:] flattens to ``_``."""
    import re as _re

    base = _re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return f"{prefix}_{base}" if prefix else base


def prometheus_text(registries, prefix: str = "geomesa") -> str:
    """Text exposition (version 0.0.4) of one or more registries.

    Counters render as ``counter``, gauges as ``gauge``, and timers as
    ``summary`` families: quantile labels in SECONDS (the exposition
    convention) from the sliding reservoir, ``_sum``/``_count`` from the
    CUMULATIVE totals (summary semantics — rate()/increase() stay
    monotone after the reservoir starts sliding), and a ``<name>_max``
    gauge. Later registries win a name collision — callers merge the
    store registry with ``robustness_metrics()`` so one scrape carries
    both query latencies and the failure-path counters."""
    if isinstance(registries, MetricsRegistry):
        registries = [registries]
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    timers: Dict[str, List[float]] = {}
    totals: Dict[str, tuple] = {}
    exemplars: Dict[str, Dict[str, Any]] = {}
    for reg in registries:
        c, g, t, tt = reg.snapshot()
        counters.update(c)
        gauges.update(g)
        timers.update({k: v for k, v in t.items() if v})
        totals.update(tt)
        exemplars.update(reg.exemplars())
    lines: List[str] = []
    for name, v in sorted(counters.items()):
        p = _prom_name(name, prefix)
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {float(v):g}")
    for name, v in sorted(gauges.items()):
        p = _prom_name(name, prefix)
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {float(v):g}")
    for name, vals in sorted(timers.items()):
        p = _prom_name(name, prefix)
        h = histogram_summary(vals)
        cum_count, cum_sum = totals.get(name, (h["count"], sum(vals)))
        lines.append(f"# TYPE {p} summary")
        # p99 exemplar as a COMMENT line: the text exposition (version
        # 0.0.4) allows only an optional timestamp after a sample value,
        # and OpenMetrics forbids exemplars on summary quantiles — an
        # inline suffix would abort the whole scrape. A '# exemplar:'
        # comment is ignored by every parser while still shipping the
        # worst bucket's (value, trace_id) link to /debug/traces in the
        # same scrape body (the full structure serves on /debug/slo).
        slot = exemplars.get(name)
        if slot and slot["buckets"]:
            s, tid, ts = slot["buckets"][max(slot["buckets"])]
            if tid:
                lines.append(
                    f'# exemplar: {p}{{quantile="0.99"}} '
                    f'trace_id="{tid}" value={s:g} ts={ts / 1000.0:.3f}'
                )
        for label, key in (("0.5", "p50_ms"), ("0.9", "p90_ms"),
                           ("0.95", "p95_ms"), ("0.99", "p99_ms")):
            lines.append(f'{p}{{quantile="{label}"}} {h[key] / 1000:g}')
        lines.append(f"{p}_sum {cum_sum:g}")
        lines.append(f"{p}_count {cum_count}")
        lines.append(f"# TYPE {p}_max gauge")
        lines.append(f"{p}_max {h['max_ms'] / 1000:g}")
    return "\n".join(lines) + "\n"


def fleet_exemplar_text(
    exemplars: Dict[str, Dict[int, tuple]], prefix: str = "geomesa"
) -> str:
    """Comment-line exposition of WORKER-minted timer exemplars (the
    fleet coordinator's ``_fleet_exemplars`` cache, parallel/fleet.py):
    worker timers live in other processes, so they cannot render as
    registry summaries here — but their worst exemplars must not
    silently vanish from the coordinator's scrape. Same '# exemplar:'
    comment discipline as ``prometheus_text`` (ignored by every parser,
    still links trace ids in the scrape body), with a ``shard`` label
    naming the worker that paid the latency."""
    lines: List[str] = []
    for timer in sorted(exemplars):
        buckets = exemplars[timer]
        if not buckets:
            continue
        s, tid, ts, shard = buckets[max(buckets)]
        if not tid:
            continue
        p = _prom_name(timer, prefix)
        lines.append(
            f'# exemplar: {p}{{quantile="0.99",shard="{int(shard)}"}} '
            f'trace_id="{tid}" value={s:g} ts={ts / 1000.0:.3f}'
        )
    return "\n".join(lines) + ("\n" if lines else "")


class PrometheusReporter(Reporter):
    """Prometheus edition of the scheduled reporters: writes the text
    exposition atomically to ``path`` on every interval (the
    node-exporter textfile-collector pattern — a scraper or sidecar
    reads the file). ``render()`` returns the same exposition on demand;
    the live pull surface is ``GET /metrics`` on web.py, which calls
    ``prometheus_text`` directly. ``extra_registries`` merge into every
    exposition (robustness_metrics() by default, so failure-path
    counters always ship alongside the store's timings)."""

    def __init__(self, registry, path: str, interval_s: float = 60.0,
                 prefix: str = "geomesa", extra_registries=None):
        super().__init__(registry, interval_s)
        self.path = path
        self.prefix = prefix
        self.extra_registries = (
            list(extra_registries) if extra_registries is not None
            else [robustness_metrics()]
        )

    def render(self) -> str:
        return prometheus_text(
            [self.registry] + self.extra_registries, prefix=self.prefix
        )

    def report_now(self) -> None:
        # render() snapshots the registries itself (it must merge the
        # extras); the base report() snapshot would only be thrown away —
        # and would sample every gauge callable twice per tick
        self.emit(None)

    def emit(self, snapshot):
        import os

        text = self.render()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(text)
        os.replace(tmp, self.path)


def _host_port(url: str, default_port: int):
    """(host, port) from a reporter url — one parse for every network
    reporter: bracketed IPv6 ([::1]:2003), host:port, or bare host
    (default port)."""
    url = url.strip()
    if url.startswith("["):  # [v6]:port or [v6]
        host, _, rest = url[1:].partition("]")
        rest = rest.lstrip(":")
        return host, int(rest) if rest else default_port
    if url.count(":") == 1:
        host, _, port = url.partition(":")
        return host, int(port)
    return url, default_port  # bare host OR unbracketed v6 literal


def reporters_from_config(
    config: Dict[str, Any], registry: MetricsRegistry, start: bool = True
):
    """Config-driven reporter construction (MetricsConfig.reporters,
    metrics/config/MetricsConfig.scala:29-50): ``config`` maps arbitrary
    reporter names to ``{"type": ..., ...}`` blocks; invalid blocks warn
    and are skipped rather than failing the rest.

    Types: console | slf4j | delimited-text | graphite | ganglia |
    prometheus. Common key: ``interval`` (seconds, default 60)."""
    import warnings

    out = []
    for key, block in config.items():
        try:
            typ = str(block["type"]).lower()
            interval = float(block.get("interval", 60.0))
            if typ == "console":
                r = ConsoleReporter(registry, interval_s=interval)
            elif typ == "slf4j":
                r = LoggingReporter(
                    registry, interval_s=interval,
                    logger_name=block.get("logger", "geomesa.metrics"),
                )
            elif typ == "delimited-text":
                r = DelimitedFileReporter(
                    registry, block["output"], interval_s=interval
                )
            elif typ == "graphite":
                host, port = _host_port(str(block["url"]), 2003)
                r = GraphiteReporter(
                    registry, host, port,
                    prefix=block.get("prefix", "geomesa"),
                    interval_s=interval,
                )
            elif typ == "ganglia":
                host, port = _host_port(str(block["url"]), 8649)
                r = GangliaReporter(
                    registry, host, port,
                    group=block.get("group", "geomesa"),
                    interval_s=interval,
                )
            elif typ == "prometheus":
                r = PrometheusReporter(
                    registry, block["output"], interval_s=interval,
                    prefix=block.get("prefix", "geomesa"),
                )
            else:
                raise ValueError(f"unknown reporter type {typ!r}")
        except Exception as e:  # noqa: BLE001 - mirror the reference's skip
            warnings.warn(f"invalid reporter config {key!r}: {e}", stacklevel=2)
            continue
        if start:
            r.start()
        out.append(r)
    return out


# -- slow-query log: bounded tail + storm guard -------------------------------
#
# The slow-query log (store/datastore._log_slow_query) renders a FULL
# span tree + plan explain per emission — exactly the thing you cannot
# afford once per query during the overload event you are trying to
# debug. The guard rate-limits full emissions to
# ``geomesa.query.slow.max.per.min`` (dropped renders counted under
# ``slowlog.dropped``), while EVERY slow query still files a cheap
# summary entry into a bounded in-memory tail — the "slow-query log
# tail" section of the /debug/report incident bundle.

_SLOWLOG_TAIL = 256
_SLOWLOG: deque = deque(maxlen=_SLOWLOG_TAIL)
_SLOWLOG_EMITS: deque = deque()  # monotonic stamps of full emissions
_SLOWLOG_LOCK = threading.Lock()


def slow_query_note(entry: Dict[str, Any]) -> bool:
    """File one slow query into the tail; True when the caller may emit
    the FULL log render (inside this minute's budget), False when the
    storm guard dropped the render (summary retained, ``dropped``
    flagged, ``slowlog.dropped`` counted)."""
    from geomesa_tpu.utils.config import SLOW_QUERY_MAX_PER_MIN

    limit = SLOW_QUERY_MAX_PER_MIN.to_int()
    limit = 60 if limit is None else limit
    now = time.monotonic()
    entry = dict(entry)
    entry.setdefault("date_ms", int(time.time() * 1000))
    with _SLOWLOG_LOCK:
        cutoff = now - 60.0
        while _SLOWLOG_EMITS and _SLOWLOG_EMITS[0] < cutoff:
            _SLOWLOG_EMITS.popleft()
        allowed = len(_SLOWLOG_EMITS) < limit
        if allowed:
            _SLOWLOG_EMITS.append(now)
        else:
            entry["dropped"] = True
        _SLOWLOG.append(entry)
    if not allowed:
        robustness_metrics().inc("slowlog.dropped")
    return allowed


def slow_query_tail(n: int = 50) -> List[Dict[str, Any]]:
    """Last ``n`` slow-query summaries (oldest first) — the incident
    report's slow-log section; entries the storm guard suppressed carry
    ``dropped: True`` (the summary survives, only the render was shed)."""
    if n <= 0:
        return []
    with _SLOWLOG_LOCK:
        return list(_SLOWLOG)[-n:]


class QueryTimeout(RuntimeError):
    """Raised when a query exceeds the store's timeout budget
    (the ThreadManagement reaper analog, index/utils/ThreadManagement.scala:
    21-60 — checked cooperatively at fault points / scan blocks / socket
    reads via ``utils.deadline`` instead of a reaper thread). A timed-out
    query fails crisply: it NEVER returns a truncated result set."""


class ShedLoad(RuntimeError):
    """Raised when admission control refuses a query outright: every
    in-flight slot is taken AND the bounded wait queue is full
    (``utils.admission``), or the brownout ladder sheds the query's
    priority class (``utils.brownout``). Deliberately fast and cheap —
    shedding exists so overload degrades to quick, honest 503s instead
    of queueing into collapse. web.py maps it to 503 + Retry-After;
    ``retry_after_s`` (when a brownout supplies its burn-derived
    backoff) overrides the header's default of 1 second."""

    retry_after_s: Optional[float] = None


class ShardUnavailable(RuntimeError):
    """Raised by the sharded scatter/gather coordinator
    (``parallel/shards.py``) when some shard's every placement — primary
    and all replicas — is refused (breaker open) or has failed. The
    partial-result policy makes this CRISP: a query either completes over
    ALL its shards (possibly via hedges and replica failovers) or raises,
    never a silently truncated result set. web.py maps it to 503 +
    Retry-After, the same backpressure idiom as ShedLoad — the shard may
    recover within a breaker cooldown."""
