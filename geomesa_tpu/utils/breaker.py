"""Circuit breakers for repeatedly failing device / network boundaries.

PR 1's degradation layer made a single device failure cheap (the query
re-answers from the host scan); this module makes a PERSISTENT failure
cheap. Without it, a dead device tunnel or unreachable broker pays the
full dispatch-and-retry cost on every query — each one rediscovering the
same outage. A ``CircuitBreaker`` remembers:

    closed     normal operation; failures accumulate in a rolling window
    open       the window filled (``failures`` within ``window_s``):
               calls short-circuit instantly for ``cooldown_s`` —
               breaker-guarded queries take their degrade path with ZERO
               per-query failure cost
    half-open  cooldown elapsed: exactly ONE probe call is let through;
               success closes the circuit (and, for the device breaker,
               the probe query rebuilds the evicted mirror), failure
               re-opens it for another cooldown

Guarded boundaries: ``device.dispatch``/``device.fetch`` (the
TpuScanExecutor's scan dispatch — open means queries go straight to the
host scan) and ``netlog.rpc`` (RemoteLogBroker — open fails fast with
``CircuitOpen`` instead of paying a full retry ladder per call).

State is observable everywhere the rest of the robustness layer already
lives: ``breaker.<name>.*`` counters and a ``breaker.<name>.state``
gauge in ``utils.audit.robustness_metrics()``, transitions as trace
events on the query that caused them, and the process-wide
``breaker_states()`` snapshot behind ``/healthz`` (degraded while any
circuit is open) and ``/debug/overload``.

Defaults come from the tiered knobs ``geomesa.breaker.failures`` /
``geomesa.breaker.window`` / ``geomesa.breaker.cooldown``
(utils/config.py); ``clock`` is injectable for deterministic tests.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, Optional

from geomesa_tpu.utils import trace
from geomesa_tpu.utils.audit import robustness_metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

# severity order for merging several same-named breakers into one report
_SEVERITY = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 0.5, OPEN: 1.0}

# every live breaker, for /healthz + /debug/overload (weak: a breaker
# dies with its executor/client and must not be pinned by telemetry)
_REGISTRY: "weakref.WeakSet[CircuitBreaker]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()


class CircuitOpen(ConnectionError):
    """Fast-fail raised at a breaker-guarded boundary while the circuit
    is open. A ConnectionError (and so an OSError): callers that already
    classify transport failures as transient treat a refused call
    exactly like the outage it stands in for — minus the latency."""


class CircuitBreaker:
    """One guarded boundary's closed/open/half-open state machine.

    ``record_failure()`` after each boundary failure, ``record_success()``
    after each success, ``allow()`` (or ``check()``, which raises
    ``CircuitOpen``) before each call. Thread-safe; all transitions and
    refusals are counted under ``breaker.<name>.*``."""

    def __init__(
        self,
        name: str,
        failures: Optional[int] = None,
        window_s: Optional[float] = None,
        cooldown_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from geomesa_tpu.utils.config import (
            BREAKER_COOLDOWN,
            BREAKER_FAILURES,
            BREAKER_WINDOW,
        )

        self.name = name
        if failures is None:
            failures = BREAKER_FAILURES.to_int() or 5
        if window_s is None:
            window_s = BREAKER_WINDOW.to_duration_s(30.0)
        if cooldown_s is None:
            cooldown_s = BREAKER_COOLDOWN.to_duration_s(5.0)
        if failures < 1:
            raise ValueError("failures must be >= 1")
        self.failures = int(failures)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._window: list = []  # monotonic stamps of recent failures
        self._opened_at = 0.0
        self._probing = False  # a half-open probe is in flight
        with _REGISTRY_LOCK:
            _REGISTRY.add(self)
        # live state gauge (same-named breakers overwrite each other;
        # breaker_states() merges them by worst state instead). Reads
        # peek_state: a metrics snapshot — the Prometheus scrape, a
        # Reporter tick, the timeline sampler — must OBSERVE the
        # breaker, never run its open->half-open transition (the next
        # real caller's allow() ticks it identically)
        ref = weakref.ref(self)
        robustness_metrics().gauge_fn(
            f"breaker.{name}.state",
            lambda: _STATE_GAUGE[ref().peek_state] if ref() is not None else 0.0,
        )

    # -- state ---------------------------------------------------------------

    def _tick_locked(self) -> None:
        """Open -> half-open once the cooldown has elapsed."""
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._state = HALF_OPEN
            self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            self._tick_locked()
            return self._state

    @property
    def peek_state(self) -> str:
        """PASSIVE state read for the telemetry sampler
        (utils/timeline.py): computes the effective state (an elapsed
        cooldown reads as half-open) WITHOUT taking the lock or running
        the open->half-open transition — a sampler tick must never
        mutate breaker state, contend with the query path, or release a
        probe slot. May lag a concurrent transition by one tick."""
        s = self._state
        if s == OPEN and self._clock() - self._opened_at >= self.cooldown_s:
            return HALF_OPEN
        return s

    def allow(self) -> bool:
        """May a call proceed? Closed: always. Open: never (counted under
        ``breaker.<name>.short_circuit``). Half-open: exactly one probe
        at a time — concurrent callers short-circuit until the probe
        reports back."""
        with self._lock:
            self._tick_locked()
            if self._state == CLOSED:
                return True
            if self._state == OPEN or self._probing:
                robustness_metrics().inc(f"breaker.{self.name}.short_circuit")
                return False
            self._probing = True
            robustness_metrics().inc(f"breaker.{self.name}.probes")
            return True

    def check(self) -> None:
        """``allow()`` that raises ``CircuitOpen`` on refusal — for
        boundaries whose contract is exception-based (the netlog RPC)."""
        if not self.allow():
            raise CircuitOpen(
                f"{self.name} circuit open "
                f"({self.failures} failures in {self.window_s:g}s; "
                f"retrying after {self.cooldown_s:g}s cooldown)"
            )

    def cancel_probe(self) -> None:
        """The call ``allow()`` admitted never actually exercised the
        guarded boundary (e.g. the device dispatcher chose a host-only
        path): release the half-open probe slot without judging the
        circuit either way. No-op in closed/open."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probing = False

    def record_success(self) -> None:
        """A guarded call succeeded. In half-open this is the probe
        reporting back: the circuit closes and the failure window
        clears. While OPEN, a straggler success (a call that dispatched
        before the trip and only finished now) is IGNORED — the cooldown
        stands; only a post-cooldown probe may close the circuit."""
        with self._lock:
            self._tick_locked()
            if self._state != HALF_OPEN:
                return
            self._state = CLOSED
            self._probing = False
            self._window.clear()
            robustness_metrics().inc(f"breaker.{self.name}.closes")
            trace.event("breaker.close", breaker=self.name)

    def reset(self) -> None:
        """Administrative close: the OPERATOR (or a supervisor that
        verified the dependency recovered out-of-band — the fleet
        restarts a worker, pings it, and re-syncs through it before
        calling this) declares the circuit healthy. Unlike
        ``record_success``, this closes from ANY state without waiting
        out the cooldown: positive external evidence outranks the
        timer. No-op when already closed with an empty window."""
        with self._lock:
            if self._state == CLOSED and not self._window:
                return
            self._state = CLOSED
            self._probing = False
            self._window.clear()
            robustness_metrics().inc(f"breaker.{self.name}.resets")
            trace.event("breaker.reset", breaker=self.name)

    def record_failure(self) -> None:
        """A guarded call failed. Half-open: the probe failed — re-open
        for another cooldown. Closed: roll the window; trip open when it
        fills."""
        with self._lock:
            self._tick_locked()
            now = self._clock()
            if self._state == HALF_OPEN:
                self._state = OPEN
                self._opened_at = now
                self._probing = False
                robustness_metrics().inc(f"breaker.{self.name}.reopens")
                trace.event("breaker.reopen", breaker=self.name)
                return
            if self._state == OPEN:
                return  # already open; nothing new to learn
            self._window.append(now)
            cutoff = now - self.window_s
            while self._window and self._window[0] < cutoff:
                self._window.pop(0)
            if len(self._window) >= self.failures:
                self._state = OPEN
                self._opened_at = now
                self._window.clear()
                robustness_metrics().inc(f"breaker.{self.name}.opens")
                trace.event(
                    "breaker.open", breaker=self.name,
                    cooldown_s=self.cooldown_s,
                )


def breaker_states() -> Dict[str, str]:
    """Every live breaker's state, worst-per-name (several executors may
    each carry a "device" breaker) — the /healthz + /debug/overload
    snapshot. A process is degraded while any circuit is open."""
    out: Dict[str, str] = {}
    with _REGISTRY_LOCK:
        live = list(_REGISTRY)
    for b in live:
        s = b.state
        if _SEVERITY[s] >= _SEVERITY.get(out.get(b.name, CLOSED), 0):
            out[b.name] = s
    return out


def peek_states() -> Dict[str, str]:
    """breaker_states() for the telemetry sampler: every live breaker's
    ``peek_state`` (passive — no transitions run, no locks taken),
    worst-per-name. The timeline must observe breakers, never drive
    them."""
    out: Dict[str, str] = {}
    with _REGISTRY_LOCK:
        live = list(_REGISTRY)
    for b in live:
        s = b.peek_state
        if _SEVERITY[s] >= _SEVERITY.get(out.get(b.name, CLOSED), 0):
            out[b.name] = s
    return out


def open_breakers() -> Dict[str, str]:
    """Just the OPEN circuits. Half-open is routine recovery probing —
    reporting it as unhealthy would keep /healthz degraded through every
    probe cycle and prolong the drain after a transient outage."""
    return {n: s for n, s in breaker_states().items() if s == OPEN}
