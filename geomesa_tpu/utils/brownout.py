"""Brownout controller: a deterministic, priority-aware overload ladder.

The flat admission gate (utils/admission.py) sheds whoever arrives after
the queue fills — a flood of background dashboard queries sheds
interactive traffic with equal probability. This module closes the loop
the telemetry already enables: each timeline tick (utils/timeline.py)
feeds the store's controller the SLO burn verdicts (utils/slo.py), the
admission queue depth, and the open-breaker count, and the controller
walks a deterministic level ladder:

* **0** — normal; the controller is a no-op.
* **1** — shed ``background`` queries.
* **2** — shed ``batch`` too, and disable the speculative load
  amplifiers: hedged shard requests (parallel/shards.py) and cold
  pyramid / join-build speculation (store/datastore.py, ops/join.py) —
  queries still answer, from the exact paths, with identical results.
* **3** — interactive + critical only, fail-fast: non-critical classes
  shed instead of queueing (a queue the burn can't drain is pure added
  latency); ``critical`` still queues and is never shed.

Levels step ONE rung at a time with enter/exit hysteresis
(``geomesa.brownout.enter.ticks`` consecutive over-target ticks to step
up, ``exit.ticks`` clear ones to step down), so one noisy second can
never flap the ladder. Every transition is a reason-coded
``decision("brownout", ...)``, a durable history record
(utils/history.py), and a named /healthz degradation; shed queries get
a crisp ``ShedLoad`` carrying a burn-derived ``Retry-After``.

The standing invariant: a brownout may cost AVAILABILITY of low-priority
classes, never correctness or critical-class availability — no level
ever changes an answer, it only refuses or de-speculates work.

Free when off: ``geomesa.brownout.enabled=0`` reduces every hot-path
hook to a cached module-flag read and keeps the controller at level 0 —
byte-identical behavior and telemetry to a build without it.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional

from geomesa_tpu.utils.audit import decision, robustness_metrics

# priority class -> the lowest brownout level that sheds it. critical
# and interactive are absent: interactive is never SHED outright (level
# 3 only stops it queueing), critical is never touched at any level.
_SHED_AT = {"background": 1, "batch": 2}
# the level that turns off hedging and cold speculative builds
_SPECULATION_OFF_LEVEL = 2
# the level that stops non-critical classes from queueing (fail-fast)
_FAIL_FAST_LEVEL = 3
_MAX_LEVEL = 3
# Retry-After ceiling: past a minute the client should re-resolve, not
# nap — and an absurd burn rate must not produce an absurd header
_RETRY_AFTER_CAP_S = 60.0

# -- the flag -----------------------------------------------------------------

_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """The hot-path gate: one module-global read once resolved."""
    e = _ENABLED
    if e is None:
        return _resolve()
    return e


def _resolve() -> bool:
    global _ENABLED
    from geomesa_tpu.utils.config import BROWNOUT_ENABLED

    _ENABLED = bool(BROWNOUT_ENABLED.to_bool())
    return _ENABLED


def set_enabled(on: Optional[bool]) -> None:
    """Flip the cached flag (``None`` re-resolves on the next read)."""
    global _ENABLED
    _ENABLED = None if on is None else bool(on)


def brownout_knobs() -> tuple:
    """(enter_ticks, exit_ticks, r1, r2, r3, retry_after_floor_s) from
    the geomesa.brownout.* tier. Explicit 0 enter/exit means "act on the
    first tick" — never ``or``-defaulted."""
    from geomesa_tpu.utils.config import (
        BROWNOUT_ENTER_TICKS,
        BROWNOUT_EXIT_TICKS,
        BROWNOUT_QUEUE_RATIO_1,
        BROWNOUT_QUEUE_RATIO_2,
        BROWNOUT_QUEUE_RATIO_3,
        BROWNOUT_RETRY_AFTER_S,
    )

    et = BROWNOUT_ENTER_TICKS.to_int()
    xt = BROWNOUT_EXIT_TICKS.to_int()
    r1 = BROWNOUT_QUEUE_RATIO_1.to_float()
    r2 = BROWNOUT_QUEUE_RATIO_2.to_float()
    r3 = BROWNOUT_QUEUE_RATIO_3.to_float()
    ra = BROWNOUT_RETRY_AFTER_S.to_float()
    return (
        2 if et is None else max(1, et),
        3 if xt is None else max(1, xt),
        0.5 if r1 is None else r1,
        0.75 if r2 is None else r2,
        0.95 if r3 is None else r3,
        1.0 if ra is None else max(0.0, ra),
    )


class BrownoutController:
    """One store's ladder state. ``on_tick`` is the only writer (driven
    by the store's timeline sampler, one thread); the query-path readers
    (``should_shed`` / ``queue_allowed`` / ``hedging_allowed`` /
    ``speculation_allowed``) are plain attribute reads — the gate costs
    nothing while the level sits at 0."""

    def __init__(self) -> None:
        self.level = 0
        self.since: Optional[float] = None  # wall time of the last raise
        self._enter_streak = 0
        self._exit_streak = 0
        self._retry_after_s: Optional[float] = None
        self._last_signals: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._history: List[Dict[str, Any]] = []  # recent transitions

    # -- query-path reads (hot; no locks) ------------------------------------

    def should_shed(self, priority: str) -> bool:
        """True when the active level sheds this priority class."""
        return self.level >= _SHED_AT.get(priority, _MAX_LEVEL + 1)

    def queue_allowed(self, priority: str) -> bool:
        """False at the fail-fast level for non-critical classes: shed
        now rather than queue behind a burn that isn't draining."""
        return priority == "critical" or self.level < _FAIL_FAST_LEVEL

    def hedging_allowed(self) -> bool:
        """Hedged shard requests re-issue work — exactly the amplifier
        to turn off while overloaded."""
        return self.level < _SPECULATION_OFF_LEVEL

    def speculation_allowed(self) -> bool:
        """Cold pyramid builds and device join-build uploads are
        throughput optimizations with exact fallbacks — deferred, not
        lost, while the ladder is at the speculation-off level."""
        return self.level < _SPECULATION_OFF_LEVEL

    def shedding_classes(self) -> List[str]:
        """The classes the active level refuses outright — the /healthz
        naming (fail-fast interactive refusals surface separately, as
        level 3 itself)."""
        lvl = self.level
        return [p for p in ("batch", "background") if lvl >= _SHED_AT[p]]

    def retry_after_s(self) -> float:
        """The burn-derived backoff shed responses carry: the worst
        violating fast-window burn rate, in whole seconds (a client of a
        14x burn waits ~14s; a queue-only brownout waits the floor)."""
        ra = self._retry_after_s
        if ra is not None:
            return ra
        return brownout_knobs()[5] or 1.0

    # -- the tick (single writer) --------------------------------------------

    def on_tick(self, store: Any) -> Optional[Dict[str, Any]]:
        """Fold this second's overload signals into the ladder. Called
        from the timeline sampler's tick with the flag already checked;
        returns the tick's brownout block (embedded in the snapshot) or
        None when the controller has nothing to report AND is at level 0.
        Never raises — the sampler's passive contract."""
        try:
            return self._tick_locked(store)
        except Exception:  # noqa: BLE001 - the recorder outlives bad signals
            return None

    def _tick_locked(self, store: Any) -> Optional[Dict[str, Any]]:
        from geomesa_tpu.utils import slo as slo_mod
        from geomesa_tpu.utils.breaker import peek_states

        enter_ticks, exit_ticks, r1, r2, r3, ra_floor = brownout_knobs()
        # signal 1: admission queue depth (lock-free peek)
        ratio = 0.0
        adm = getattr(store, "admission", None)
        if adm is not None and adm.max_queue > 0:
            ratio = adm.peek().get("queued", 0) / float(adm.max_queue)
        # signal 2: SLO burn (create=False — a tick must never be what
        # spawns telemetry state; without an engine the signal is quiet)
        violating: List[str] = []
        max_burn = 0.0
        eng = slo_mod.engine_for(store, create=False)
        if eng is not None:
            ev = eng.evaluate(exemplars=False)
            violating = ev.get("violating", [])
            for row in ev.get("slos", ()):
                if row.get("violating"):
                    max_burn = max(
                        max_burn, row.get("fast", {}).get("burn_rate", 0.0)
                    )
        # signal 3: open breakers (passive peek — no transitions)
        open_breakers = sorted(
            n for n, st in peek_states().items() if st == "open"
        )
        # deterministic target: queue depth sets the base rung, a
        # burning SLO escalates one rung past it (latency is hurting
        # even where the queue isn't deep yet), open breakers under
        # pressure force at least the speculation-off rung (stop
        # re-issuing work against a fabric that is already failing)
        target = 0
        if ratio >= r1:
            target = 1
        if ratio >= r2:
            target = 2
        if ratio >= r3:
            target = 3
        if violating:
            target = min(_MAX_LEVEL, target + 1) if target else 1
        if open_breakers and target:
            target = max(target, _SPECULATION_OFF_LEVEL)
        with self._lock:
            self._retry_after_s = (
                max(ra_floor, min(_RETRY_AFTER_CAP_S, math.ceil(max_burn)))
                if max_burn > 0.0
                else max(1.0, ra_floor)
            )
            self._last_signals = {
                "queue_ratio": round(ratio, 3),
                "slo_violating": violating,
                "open_breakers": open_breakers,
                "target": target,
            }
            if target > self.level:
                self._enter_streak += 1
                self._exit_streak = 0
                if self._enter_streak >= enter_ticks:
                    self._transition(store, self.level + 1, target)
                    self._enter_streak = 0
            elif target < self.level:
                self._exit_streak += 1
                self._enter_streak = 0
                if self._exit_streak >= exit_ticks:
                    self._transition(store, self.level - 1, target)
                    self._exit_streak = 0
            else:
                self._enter_streak = 0
                self._exit_streak = 0
            if self.level == 0 and target == 0 and not self._history:
                return None  # quiet store: the tick stays byte-identical
            return self._block_locked()

    def _transition(self, store: Any, new_level: int, target: int) -> None:
        """One rung up or down: reason-coded decision, durable history
        record, counters. Runs under the controller lock on the sampler
        thread."""
        old = self.level
        self.level = new_level
        self.since = time.time() if new_level > 0 else None
        reason = "raise" if new_level > old else "lower"
        sig = self._last_signals
        decision(
            "brownout",
            reason,
            level=new_level,
            target=target,
            queue_ratio=sig.get("queue_ratio"),
            slo=",".join(sig.get("slo_violating", ())[:4]),
            breakers=len(sig.get("open_breakers", ())),
        )
        robustness_metrics().inc(f"brownout.level.{new_level}")
        rec = {
            "kind": "brownout",
            "t": time.time(),
            "level": new_level,
            "from": old,
            "target": target,
            **{k: v for k, v in sig.items() if k != "target"},
        }
        self._history.append(rec)
        del self._history[:-16]
        # durable record (utils/history.py) — create=False: a brownout
        # transition must never be what opens the spool
        try:
            from geomesa_tpu.utils import history as history_mod

            spool = history_mod.spool_for(store, create=False)
            if spool is not None:
                spool.append(rec)
        except Exception:  # noqa: BLE001 - telemetry must not break the tick
            pass

    # -- observability -------------------------------------------------------

    def _block_locked(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "level": self.level,
            **self._last_signals,
        }
        if self.since is not None:
            out["since"] = round(self.since, 3)
        out["retry_after_s"] = self._retry_after_s
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/brownout body: the live ladder state, the signals
        the last tick saw, the sheds-by-class counters, and the recent
        transition history."""
        counters, _g, _t, _tt = robustness_metrics().snapshot()
        with self._lock:
            return {
                "enabled": enabled(),
                "level": self.level,
                "since": self.since,
                "signals": dict(self._last_signals),
                "retry_after_s": self._retry_after_s,
                "enter_streak": self._enter_streak,
                "exit_streak": self._exit_streak,
                "transitions": list(self._history),
                "counters": {
                    k: v
                    for k, v in sorted(counters.items())
                    if k.startswith(("brownout.", "shed.priority."))
                },
            }


def controller_for(store: Any) -> Optional[BrownoutController]:
    """The store's controller, or None — the duck-typed accessor the
    web/timeline surfaces share (workers' partition sub-stores have no
    controller of their own; the coordinator's decides)."""
    return getattr(store, "_brownout", None)
