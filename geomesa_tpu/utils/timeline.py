"""Flight-recorder telemetry timeline: a fixed-memory ring of per-second
deltas over every registry.

PRs 2-3 made the system observable point-in-time (`/metrics`, span
trees, cost receipts) — but the first question of any incident is *"what
changed in the last 60 seconds?"*, and a Prometheus scrape interval is
too coarse (and external) to answer it from inside the process. This
module is the continuous layer: a ``TimelineSampler`` daemon thread
snapshots, once per ``geomesa.timeline.interval``,

* **counter deltas** of every registry the store's telemetry lands in
  (the store's own ``MetricsRegistry``, ``robustness_metrics()``,
  ``devstats_metrics()``) — only the counters that MOVED, so an idle
  store's snapshots stay tiny;
* **gauge values** (HBM residency, pad efficiency, cache sizes, ...);
* **timer activity**: per-timer count/sum deltas plus a power-of-two
  latency-bucket histogram of the interval's new samples (the shared
  ``audit.exemplar_bucket`` rule — the SLO engine evaluates latency
  objectives over any window by summing these buckets);
* **breaker states** (``breaker.peek_states`` — PASSIVE reads: the
  sampler never runs a transition, never releases a probe slot);
* **admission depth** (``AdmissionController.peek`` — LOCK-FREE reads:
  the sampler never contends with, let alone holds, the queue);
* **cache hit/miss deltas** for the aggregate pyramid, join build, and
  query-coalescing layers, with derived hit rates;
* a per-shard rollup when the store is a ``ShardedDataStore``
  (``_timeline_extra`` — each worker's telemetry gathered through the
  worker-facing seam a cross-process transport would RPC).

The ring covers ``geomesa.timeline.window`` (default 1 hour at 1 s
ticks) and is served as ``GET /debug/timeline?s=60`` (web.py), embedded
in bench artifacts (scripts/bench_gate.py), and bundled into the
one-shot incident report (``GET /debug/report``).

Free when off: ``geomesa.timeline.enabled=0`` starts no thread, and the
only hot-path hook in the whole subsystem — the timer exemplar record in
``audit.MetricsRegistry.update_timer`` — stays behind a single
module-flag read (asserted by tests/test_timeline.py). The sampler
itself only ever READS: it must never strike a breaker, hold the
admission queue, or touch a fault point (chaos-soaked in
tests/test_timeline.py via scripts/chaos_smoke.sh).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from geomesa_tpu.utils import audit
from geomesa_tpu.utils.audit import MetricsRegistry

_log = logging.getLogger("geomesa_tpu.timeline")

# cache-layer counter pairs surfaced as per-tick hit rates: the bimodal
# latency story (pyramid hit vs exact scan, coalesced vs solo) is
# unreadable from aggregate percentiles alone
_CACHE_RATES = (
    ("agg", "agg.cache.hits", "agg.cache.misses"),
    ("join_build", "join.build.hits", "join.build.misses"),
)


def timeline_knobs() -> tuple:
    """(enabled, interval_s, window_s) from the geomesa.timeline.* tier."""
    from geomesa_tpu.utils.config import (
        TIMELINE_ENABLED,
        TIMELINE_INTERVAL,
        TIMELINE_WINDOW,
    )

    enabled = bool(TIMELINE_ENABLED.to_bool())
    interval_s = TIMELINE_INTERVAL.to_duration_s(1.0)
    window_s = TIMELINE_WINDOW.to_duration_s(3600.0)
    return enabled, max(0.01, interval_s), max(interval_s, window_s)


class TimelineSampler:
    """One store's flight recorder: a daemon thread appending per-tick
    delta snapshots to a bounded ring.

    ``tick()`` is callable directly (tests drive it deterministically);
    ``start()`` runs it on the interval. The sampler holds the store
    WEAKLY — telemetry must never pin a store's tables and mirrors —
    and the thread exits once the store is collected."""

    def __init__(
        self,
        store: Any = None,
        registries: Optional[List[MetricsRegistry]] = None,
        interval_s: Optional[float] = None,
        window_s: Optional[float] = None,
    ):
        _enabled, k_interval, k_window = timeline_knobs()
        self.interval_s = k_interval if interval_s is None else float(interval_s)
        self.window_s = k_window if window_s is None else float(window_s)
        self._store = (lambda: None) if store is None else weakref.ref(store)
        if registries is None:
            from geomesa_tpu.utils.audit import robustness_metrics
            from geomesa_tpu.utils.devstats import devstats_metrics

            registries = [robustness_metrics(), devstats_metrics()]
            m = getattr(store, "metrics", None)
            if isinstance(m, MetricsRegistry):
                # the store registry FIRST: its query.* names must win a
                # (never expected) collision with the process registries
                registries.insert(0, m)
        self.registries = list(registries)
        capacity = max(2, int(round(self.window_s / self.interval_s)))
        self._ring: deque = deque(maxlen=capacity)
        self._prev_counters: Dict[str, int] = {}
        self._prev_totals: Dict[str, tuple] = {}
        self._prev_plans: Dict[str, tuple] = {}
        self._prev_tenants: Dict[str, tuple] = {}
        self._primed = False
        self.ticks = 0  # cumulative, survives ring rotation
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the durable telemetry spool (utils/history.py), attached by
        # sampler_for when geomesa.history.enabled and the store has a
        # durable root; None keeps the hook a single attribute read
        self._history: Optional[Any] = None

    # -- sampling ------------------------------------------------------------

    def _merged_snapshot(self):
        counters: Dict[str, int] = {}
        gauges: Dict[str, float] = {}
        timers: Dict[str, List[float]] = {}
        totals: Dict[str, tuple] = {}
        # later registries must NOT overwrite the store's own names, so
        # iterate in reverse priority (store registry listed first wins)
        for reg in reversed(self.registries):
            c, g, t, tt = reg.snapshot()
            counters.update(c)
            gauges.update(g)
            timers.update(t)
            totals.update(tt)
        return counters, gauges, timers, totals

    def tick(self) -> Optional[Dict[str, Any]]:
        """Take one snapshot (append to the ring, return it). Never
        raises — a telemetry failure must not kill the recorder loop —
        and only ever READS the layers it observes."""
        try:
            snap = self._tick()
        except Exception:  # noqa: BLE001 - recorder must outlive bad gauges
            _log.exception("timeline tick failed; recording continues")
            return None
        # write-behind durability (utils/history.py): feed the spool
        # AFTER the ring append and OUTSIDE the sampler lock — a wedged
        # flush (bounded by its own budget) must never block window()
        # readers, and the ring stays the source of truth
        hist = self._history
        if hist is not None:
            try:
                hist.on_tick(snap, self._store())
            except Exception:  # noqa: BLE001 - spool failures never stop ticks
                _log.exception("history spool tick failed; recording continues")
        # workload-capture drain (utils/workload.py): same write-behind
        # posture, its OWN spool — history may be off while capture is
        # on. create=False: a tick must never be what opens the spool.
        try:
            from geomesa_tpu.utils import workload as _workload

            _workload.flush_for(self._store())
        except Exception:  # noqa: BLE001 - spool failures never stop ticks
            _log.exception("workload spool tick failed; recording continues")
        return snap

    def _tick(self) -> Dict[str, Any]:
        from geomesa_tpu.utils.breaker import peek_states

        counters, gauges, timers, totals = self._merged_snapshot()
        # the brownout control loop RUNS on this tick (the one
        # deliberate exception to the watches-never-drives rule: the
        # ladder needs exactly one periodic evaluation point, and the
        # sampler is it). OUTSIDE the ring lock — the controller reads
        # the SLO engine, whose window() copy takes this same lock.
        # Returns None for a quiet healthy store, keeping the tick
        # byte-identical; geomesa.brownout.enabled=0 never evaluates
        bblock = None
        _store0 = self._store()
        if _store0 is not None:
            bo = getattr(_store0, "_brownout", None)
            if bo is not None:
                from geomesa_tpu.utils import brownout as _brownout

                if _brownout.enabled():
                    bblock = bo.on_tick(_store0)
        with self._lock:
            snap: Dict[str, Any] = {
                "t": time.time(),
                "dt_s": round(self.interval_s, 3),
            }
            if self._primed:
                deltas = {
                    k: v - self._prev_counters.get(k, 0)
                    for k, v in counters.items()
                    if v != self._prev_counters.get(k, 0)
                }
            else:
                # first tick: establish the baseline, report no deltas
                # (a process's whole history is not "the last second")
                deltas = {}
            snap["counters"] = deltas
            snap["gauges"] = {k: v for k, v in gauges.items()}
            tblock: Dict[str, Any] = {}
            for name, (count, total_s) in totals.items():
                pc, ps = self._prev_totals.get(name, (0, 0.0))
                k = count - pc
                if k <= 0 or not self._primed:
                    continue
                hist: Dict[int, int] = {}
                # the interval's new samples are the reservoir tail —
                # exact while fewer than RESERVOIR samples land per tick
                # (4096/s; far past any load this process serves)
                for s in timers.get(name, [])[-k:]:
                    b = audit.exemplar_bucket(s)
                    hist[b] = hist.get(b, 0) + 1
                tblock[name] = {
                    "count": k,
                    "sum_ms": round((total_s - ps) * 1000.0, 3),
                    "hist": hist,
                }
            snap["timers"] = tblock
            snap["caches"] = self._cache_rates(deltas)
            self._prev_counters = counters
            self._prev_totals = totals
            was_primed = self._primed
            self._primed = True
            # passive observations: peek_states runs no transitions,
            # peek() takes no locks — the recorder watches, never drives
            snap["breakers"] = peek_states()
            store = self._store()
            if store is not None:
                adm = getattr(store, "admission", None)
                if adm is not None:
                    snap["admission"] = adm.peek()
                # per-tick top plan-fingerprint deltas (utils/plans.py):
                # "which plan shapes were hot THIS second". Reads the
                # registry only if the store already HAS one — a sampler
                # tick must never be what creates telemetry state
                preg = getattr(store, "_plans", None)
                if preg is not None:
                    from geomesa_tpu.utils import plans as _plans

                    self._prev_plans, prows = _plans.timeline_deltas(
                        preg, self._prev_plans
                    )
                    # first tick primes the baseline, reports nothing
                    # (the counter-delta rule above)
                    if prows and was_primed:
                        snap["plans"] = prows
                # per-tick per-tenant deltas (utils/tenants.py): "whose
                # traffic was THIS second" — same never-creates posture
                treg = getattr(store, "_tenants", None)
                if treg is not None:
                    from geomesa_tpu.utils import tenants as _tenants

                    self._prev_tenants, trows = _tenants.timeline_deltas(
                        treg, self._prev_tenants
                    )
                    if trows and was_primed:
                        snap["tenants"] = trows
                extra = getattr(store, "_timeline_extra", None)
                if extra is not None:
                    snap.update(extra())
                if bblock is not None:
                    snap["brownout"] = bblock
            self._ring.append(snap)
            self.ticks += 1
            return snap

    @staticmethod
    def _cache_rates(deltas: Dict[str, int]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for label, hits_c, miss_c in _CACHE_RATES:
            hits = deltas.get(hits_c, 0)
            misses = deltas.get(miss_c, 0)
            if hits or misses:
                out[label] = {
                    "hits": hits,
                    "misses": misses,
                    "rate": round(hits / (hits + misses), 3),
                }
        groups = deltas.get("batch.coalesce.groups", 0)
        members = deltas.get("batch.coalesce.members", 0)
        if groups:
            out["coalesce"] = {
                "groups": groups,
                "members": members,
                "mean_group": round(members / groups, 2),
            }
        return out

    # -- ring access ---------------------------------------------------------

    def window(self, s: Optional[float] = None) -> List[Dict[str, Any]]:
        """The last ``s`` seconds of snapshots (oldest first; the whole
        ring when ``s`` is None). Copies under the lock — a concurrent
        tick can never mutate what a reader is serializing."""
        with self._lock:
            snaps = list(self._ring)
        if s is None:
            return snaps
        n = max(1, int(round(float(s) / self.interval_s)))
        return snaps[-n:]

    def payload(self, s: Optional[float] = 60.0) -> Dict[str, Any]:
        """The GET /debug/timeline body."""
        snaps = self.window(s)
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "window_s": self.window_s,
            "ticks": self.ticks,
            "returned": len(snaps),
            "snapshots": snaps,
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TimelineSampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        ref = weakref.ref(self)

        def loop():
            # tick-cost compensation: waiting the FULL interval after
            # tick work makes every cycle last interval + tick_cost, so
            # timestamps drift and an hour's ring covers less than an
            # hour. Subtract the previous tick's cost from the wait
            # (floored at 0: a tick slower than the interval ticks
            # again immediately, it cannot wait a negative time).
            elapsed = 0.0
            while True:
                me = ref()
                if me is None:
                    return
                stop, interval = me._stop, me.interval_s
                store_dead = (
                    isinstance(me._store, weakref.ref)
                    and me._store() is None
                )
                del me  # the loop must not pin the sampler between ticks
                if store_dead:
                    return  # telemetry dies with (never outlives) its store
                if stop.wait(max(0.0, interval - elapsed)):
                    return
                me = ref()
                if me is None:
                    return
                t0 = time.monotonic()
                me.tick()
                elapsed = time.monotonic() - t0
                del me

        t = threading.Thread(
            target=loop, name="geomesa-timeline", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)


def merge_worker_ticks(workers: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-worker flight-recorder ticks (the fleet ``timeline`` RPC
    replies, parallel/fleet.py) into one fleet-rollup block:

    * **counters** — per-tick deltas SUM across workers (additive by
      construction);
    * **timers** — count/sum_ms sum, latency-bucket histograms merged
      bucket-wise (so the SLO bucket rule applies to the rollup too);
    * **breakers** — only each worker's NON-closed breakers, keyed by
      worker (a silently degrading worker — device breaker open, host
      scans — becomes visible from the coordinator);
    * **unreachable** — workers whose tick did not answer under the
      passive budget;
    * **per_worker** — each reachable worker's UNMERGED counter/timer
      series, keyed by worker. The SLO engine burns these individually:
      a single sick worker must violate its class objective even when
      the fleet-summed histogram dilutes it below threshold (the skew a
      sum can never show).

    Gauges are deliberately NOT rolled up: summing HBM residency or pad
    ratios across processes is a lie; the per-worker blocks keep them."""
    rollup: Dict[str, Any] = {
        "workers": 0,
        "counters": {},
        "timers": {},
        "breakers": {},
        "unreachable": [],
        "per_worker": {},
    }
    counters = rollup["counters"]
    timers = rollup["timers"]
    for wid in sorted(workers):
        row = workers[wid]
        if not isinstance(row, dict) or row.get("unreachable"):
            rollup["unreachable"].append(wid)
            continue
        rollup["workers"] += 1
        tick = row.get("tick") or {}
        w_counters: Dict[str, int] = {}
        w_timers: Dict[str, Any] = {}
        for k, v in (tick.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
            w_counters[k] = int(v)
        for name, t in (tick.get("timers") or {}).items():
            acc = timers.setdefault(
                name, {"count": 0, "sum_ms": 0.0, "hist": {}}
            )
            acc["count"] += int(t.get("count", 0))
            acc["sum_ms"] = round(
                acc["sum_ms"] + float(t.get("sum_ms", 0.0)), 3
            )
            hist = {str(b): int(n) for b, n in (t.get("hist") or {}).items()}
            for b, n in hist.items():
                acc["hist"][b] = acc["hist"].get(b, 0) + n
            w_timers[name] = {
                "count": int(t.get("count", 0)),
                "sum_ms": round(float(t.get("sum_ms", 0.0)), 3),
                "hist": hist,
            }
        if w_counters or w_timers:
            rollup["per_worker"][wid] = {
                "counters": w_counters,
                "timers": w_timers,
            }
        open_b = sorted(
            name
            for name, state in (tick.get("breakers") or {}).items()
            if state != "closed"
        )
        if open_b:
            rollup["breakers"][wid] = open_b
    return rollup


# -- per-store samplers -------------------------------------------------------
#
# One sampler per store, refcounted like trace.ensure_ring: each server
# (web.GeoMesaServer) holds one reference, the last release stops the
# thread and — when no sampler remains anywhere — drops the process-wide
# exemplar flag back to the free-when-off state.

_SAMPLERS: "weakref.WeakKeyDictionary[Any, TimelineSampler]" = (
    weakref.WeakKeyDictionary()
)
_REFS: "weakref.WeakKeyDictionary[Any, int]" = weakref.WeakKeyDictionary()
_SAMPLERS_LOCK = threading.Lock()


def _exemplars_wanted() -> bool:
    from geomesa_tpu.utils.config import SLO_EXEMPLARS

    return bool(SLO_EXEMPLARS.to_bool())


def sampler_for(store, create: bool = True) -> Optional[TimelineSampler]:
    """The store's running sampler; started on first request when
    ``geomesa.timeline.enabled`` (None otherwise, and None with
    ``create=False`` when none exists yet). Starting the first sampler
    also raises the timer-exemplar flag (``geomesa.slo.exemplars``) so
    /debug/slo has traces to link; stopping the last drops it."""
    with _SAMPLERS_LOCK:
        got = _SAMPLERS.get(store)
        if got is not None or not create:
            return got
        enabled, _i, _w = timeline_knobs()
        if not enabled:
            return None
        sampler = TimelineSampler(store)
        # durable telemetry (utils/history.py): stores with a durable
        # root get their ticks spooled write-behind; spool_for answers
        # None (and the tick hook stays one attribute read) when
        # geomesa.history.enabled=0 or the store is memory-only
        from geomesa_tpu.utils import history as _history

        sampler._history = _history.spool_for(store)
        _SAMPLERS[store] = sampler
        _REFS[store] = 0
        if _exemplars_wanted():
            audit.set_exemplars(True)
    sampler.start()
    return sampler


def acquire(store) -> Optional[TimelineSampler]:
    """sampler_for + one refcount (a server's hold on the recorder)."""
    got = sampler_for(store)
    if got is not None:
        with _SAMPLERS_LOCK:
            _REFS[store] = _REFS.get(store, 0) + 1
    return got


def release(store) -> None:
    """Drop one server's hold; the last release stops the store's
    sampler and, when no sampler remains for ANY store, restores the
    exemplar hook to its free no-op path."""
    stop_me = None
    with _SAMPLERS_LOCK:
        if store not in _SAMPLERS:
            return
        refs = _REFS.get(store, 0) - 1
        if refs > 0:
            _REFS[store] = refs
            return
        stop_me = _SAMPLERS.pop(store, None)
        _REFS.pop(store, None)
        if not _SAMPLERS:
            audit.set_exemplars(False)
    if stop_me is not None:
        stop_me.stop()
