"""Minimal Avro Object Container File codec (pure Python).

Supports the subset of the Avro 1.x spec the converter and export layers
need — primitive types, records, arrays, maps, unions, enums, fixed, and
the null/deflate block codecs — replacing the reference's dependency on the
Java Avro library (geomesa-convert-avro AvroConverter, geomesa-features
AvroFeatureSerializer). Schemas are plain JSON per the spec.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, List, Optional, Tuple, Union

MAGIC = b"Obj\x01"


# -- zigzag varint ------------------------------------------------------------


def _read_long(fh: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = fh.read(1)
        if not b:
            raise EOFError("truncated avro varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: BinaryIO, value: int) -> None:
    n = (value << 1) ^ (value >> 63)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            break


# -- datum reader/writer ------------------------------------------------------


def _read_datum(fh: BinaryIO, schema: Any) -> Any:
    if isinstance(schema, str):
        kind = schema
    elif isinstance(schema, list):  # union: long index then datum
        idx = _read_long(fh)
        return _read_datum(fh, schema[idx])
    else:
        kind = schema["type"]
    if kind == "null":
        return None
    if kind == "boolean":
        return fh.read(1) == b"\x01"
    if kind in ("int", "long"):
        return _read_long(fh)
    if kind == "float":
        return struct.unpack("<f", fh.read(4))[0]
    if kind == "double":
        return struct.unpack("<d", fh.read(8))[0]
    if kind == "bytes":
        return fh.read(_read_long(fh))
    if kind == "string":
        return fh.read(_read_long(fh)).decode("utf-8")
    if kind == "record":
        return {f["name"]: _read_datum(fh, f["type"]) for f in schema["fields"]}
    if kind == "enum":
        return schema["symbols"][_read_long(fh)]
    if kind == "fixed":
        return fh.read(schema["size"])
    if kind == "array":
        out: List[Any] = []
        while True:
            n = _read_long(fh)
            if n == 0:
                break
            if n < 0:  # block with byte size
                _read_long(fh)
                n = -n
            for _ in range(n):
                out.append(_read_datum(fh, schema["items"]))
        return out
    if kind == "map":
        m: Dict[str, Any] = {}
        while True:
            n = _read_long(fh)
            if n == 0:
                break
            if n < 0:
                _read_long(fh)
                n = -n
            for _ in range(n):
                k = fh.read(_read_long(fh)).decode("utf-8")
                m[k] = _read_datum(fh, schema["values"])
        return m
    raise ValueError(f"unsupported avro type: {kind!r}")


def _write_datum(out: BinaryIO, schema: Any, value: Any) -> None:
    if isinstance(schema, list):  # union: pick the first matching branch
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                _write_long(out, i)
                _write_datum(out, branch, value)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema}")
    kind = schema if isinstance(schema, str) else schema["type"]
    if kind == "null":
        return
    if kind == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif kind in ("int", "long"):
        _write_long(out, int(value))
    elif kind == "float":
        out.write(struct.pack("<f", float(value)))
    elif kind == "double":
        out.write(struct.pack("<d", float(value)))
    elif kind == "bytes":
        _write_long(out, len(value))
        out.write(value)
    elif kind == "string":
        raw = str(value).encode("utf-8")
        _write_long(out, len(raw))
        out.write(raw)
    elif kind == "record":
        for f in schema["fields"]:
            _write_datum(out, f["type"], value.get(f["name"]))
    elif kind == "enum":
        _write_long(out, schema["symbols"].index(value))
    elif kind == "fixed":
        out.write(value)
    elif kind == "array":
        if value:
            _write_long(out, len(value))
            for v in value:
                _write_datum(out, schema["items"], v)
        _write_long(out, 0)
    elif kind == "map":
        if value:
            _write_long(out, len(value))
            for k, v in value.items():
                raw = str(k).encode("utf-8")
                _write_long(out, len(raw))
                out.write(raw)
                _write_datum(out, schema["values"], v)
        _write_long(out, 0)
    else:
        raise ValueError(f"unsupported avro type: {kind!r}")


def _matches(schema: Any, value: Any) -> bool:
    kind = schema if isinstance(schema, str) else schema["type"]
    if kind == "null":
        return value is None
    if value is None:
        return False
    if kind == "boolean":
        return isinstance(value, bool)
    if kind in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if kind in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if kind == "string":
        return isinstance(value, str)
    if kind in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if kind == "record":
        return isinstance(value, dict)
    if kind == "array":
        return isinstance(value, (list, tuple))
    if kind == "map":
        return isinstance(value, dict)
    if kind == "enum":
        return isinstance(value, str)
    return False


# -- object container files ---------------------------------------------------


def read_container(source: Union[str, BinaryIO]) -> Tuple[Any, Iterator[Any]]:
    """(schema, record iterator) from an Avro OCF (null/deflate codecs)."""
    fh = open(source, "rb") if isinstance(source, str) else source

    if fh.read(4) != MAGIC:
        raise ValueError("not an avro object container file")
    meta = _read_datum(fh, {"type": "map", "values": "bytes"})
    schema = json.loads(meta[b"avro.schema"] if b"avro.schema" in meta else meta["avro.schema"])
    codec = meta.get("avro.codec", b"null")
    codec = codec.decode() if isinstance(codec, (bytes, bytearray)) else codec
    sync = fh.read(16)

    def records() -> Iterator[Any]:
        try:
            while True:
                try:
                    count = _read_long(fh)
                except EOFError:
                    return
                size = _read_long(fh)
                block = fh.read(size)
                if codec == "deflate":
                    block = zlib.decompress(block, -15)
                elif codec != "null":
                    raise ValueError(f"unsupported avro codec: {codec}")
                bio = io.BytesIO(block)
                for _ in range(count):
                    yield _read_datum(bio, schema)
                if fh.read(16) != sync:
                    raise ValueError("avro sync marker mismatch")
        finally:
            if isinstance(source, str):
                fh.close()

    return schema, records()


def write_container(
    sink: Union[str, BinaryIO],
    schema: Any,
    records: Iterator[Any],
    codec: str = "null",
    block_size: int = 1000,
) -> int:
    """Write records as an Avro OCF; returns the record count."""
    fh = open(sink, "wb") if isinstance(sink, str) else sink
    try:
        fh.write(MAGIC)
        meta = {
            "avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode(),
        }
        _write_datum(fh, {"type": "map", "values": "bytes"}, meta)
        sync = os.urandom(16)
        fh.write(sync)
        total = 0
        buf: List[Any] = []

        def flush():
            nonlocal total
            if not buf:
                return
            bio = io.BytesIO()
            for r in buf:
                _write_datum(bio, schema, r)
            payload = bio.getvalue()
            if codec == "deflate":
                co = zlib.compressobj(wbits=-15)
                payload = co.compress(payload) + co.flush()
            _write_long(fh, len(buf))
            _write_long(fh, len(payload))
            fh.write(payload)
            fh.write(sync)
            total += len(buf)
            buf.clear()

        for r in records:
            buf.append(r)
            if len(buf) >= block_size:
                flush()
        flush()
        return total
    finally:
        if isinstance(sink, str):
            fh.close()
