"""Device & compiler telemetry: recompile accounting, transfer byte
counters, padding efficiency, and best-effort HBM gauges.

PR 2 made every query a span tree and every boundary a metric, but the
device layer underneath stayed a black box: ~30 ``jax.jit`` sites behind
shape-bucketed caches, where a silent recompile or a padding blow-up
costs more than anything the host-side spans can see. This module is the
measurement substrate underneath those spans:

* ``instrumented_jit(name, fn, **jit_kw)`` — the ONLY sanctioned way to
  jit in ``geomesa_tpu/`` (enforced by scripts/lint_observability.sh).
  It models the jit cache with the argument signature (shapes + dtypes +
  static values) and, on each first-seen signature, wraps the triggering
  call in an ``xla.compile`` span so the compile attributes to the QUERY
  that paid for it, bumps ``xla.compile.<name>`` / ``xla.compile.total``
  counters, and feeds the ``xla.compile`` wall-time timer. A per-kernel
  cache-entry gauge (``xla.cache.<name>.entries``) tracks bucket growth.
* monotone ``device.h2d.bytes`` / ``device.d2h.bytes`` counters, fed by
  the dispatch/fetch boundaries (parallel/mesh.py shard_array/replicate,
  parallel/executor._np_local) that already carry per-trace byte attrs.
* padding-efficiency gauges (``device.pad.*``): rows used vs. the pow2
  capacity bucket of the latest segment upload, plus monotone row
  totals so a fleet-wide pad regression shows up in rate() form.
* best-effort HBM gauges: ``device.hbm.live_bytes`` from
  ``jax.live_arrays()`` and ``device.hbm.bytes_in_use`` /
  ``device.hbm.peak_bytes_in_use`` from ``Device.memory_stats()`` when
  the backend provides it (TPU/GPU do; CPU reads 0).

Everything lands in one process-wide ``MetricsRegistry``
(``devstats_metrics()``, the ``robustness_metrics()`` posture) so the
existing reporters/exposition carry it for free; web.py merges it into
``GET /metrics`` and serves a structured ``GET /debug/device``.

Per-query attribution rides the "cost receipt": ``receipt_snapshot()``
before execution, ``receipt_since()`` after — the delta (recompiles
triggered, bytes moved each way, current pad ratio) attaches to the
query's root span, the QueryEvent audit row, and therefore the
slow-query log. Counters are process-wide, so under concurrent query
streams a receipt is an upper bound on what THIS query caused — exact
on the single-stream bench/CI paths the perf gate
(scripts/bench_gate.py) runs.
"""

from __future__ import annotations

import contextvars
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from geomesa_tpu.utils import trace
from geomesa_tpu.utils.audit import MetricsRegistry

_DEVSTATS: Optional[MetricsRegistry] = None
_DEVSTATS_LOCK = threading.Lock()

# kernel name -> _KernelStats; shared by every instrumented_jit wrapper
# carrying that name (the executor builds one wrapper per cache key, but
# accounting is per KERNEL — that is the unit an operator reasons about)
_KERNELS: Dict[str, "_KernelStats"] = {}
_KERNELS_LOCK = threading.Lock()


def devstats_metrics() -> MetricsRegistry:
    """Process-wide device/compiler telemetry registry:

        xla.compile.<name>        compiles per kernel name (counter)
        xla.compile.total         compiles across every kernel (counter)
        xla.compile               compile wall time (timer percentiles)
        xla.cache.<name>.entries  live cache signatures per kernel (gauge)
        xla.cache.entries         sum across kernels (gauge)
        device.h2d.bytes          host->device bytes, monotone (counter)
        device.d2h.bytes          device->host bytes, monotone (counter)
        device.pad.rows_used      latest segment upload's real rows (gauge)
        device.pad.rows_capacity  its pow2 capacity bucket (gauge)
        device.pad.ratio          used / capacity of that upload (gauge)
        device.pad.rows_used_total / rows_padded_total   monotone totals
        device.hbm.live_bytes     sum of jax.live_arrays() nbytes (gauge)
        device.hbm.bytes_in_use / peak_bytes_in_use      backend stats

    One shared registry rather than per-store for the same reason as
    robustness_metrics(): the jit caches and the mesh dispatch helpers
    live below the store facade and are shared across stores."""
    global _DEVSTATS
    with _DEVSTATS_LOCK:
        if _DEVSTATS is None:
            reg = MetricsRegistry()
            reg.gauge_fn("xla.cache.entries", _total_cache_entries)
            reg.gauge_fn("device.hbm.live_bytes", _live_array_bytes)
            reg.gauge_fn("device.hbm.bytes_in_use",
                         lambda: _memory_stat("bytes_in_use"))
            reg.gauge_fn("device.hbm.peak_bytes_in_use",
                         lambda: _memory_stat("peak_bytes_in_use"))
            _DEVSTATS = reg
        return _DEVSTATS


def _total_cache_entries() -> int:
    with _KERNELS_LOCK:
        stats = list(_KERNELS.values())
    return sum(s.cache_entries() for s in stats)


def _live_array_bytes() -> int:
    """Best-effort HBM residency: bytes held by live jax arrays. On CPU
    this is host memory, but the shape of the number (mirror growth,
    leak detection) is what the gauge is for."""
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:  # noqa: BLE001 - a deleted/donated array mid-walk
            pass
    return total


def _memory_stat(key: str) -> int:
    """Sum one Device.memory_stats() field across devices; backends
    without stats (CPU) read 0 rather than failing the snapshot."""
    import jax

    total = 0
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - backend without the API
            stats = None
        if stats:
            total += int(stats.get(key, 0))
    return total


class _SigSet(set):
    """One wrapper's seen-signature set. A plain ``set`` cannot be
    weakly referenced; this subclass can, so the kernel aggregate holds
    them via a WeakSet and a dropped wrapper's buckets leave the
    cache-entry gauge. Identity hashing (sets are unhashable by value)
    is exactly right: each wrapper's set is a distinct member."""

    __hash__ = object.__hash__


class _KernelStats:
    """Per-kernel-NAME aggregation over per-WRAPPER signature sets.

    jit's compilation cache is per wrapper, and the executor deliberately
    builds many wrappers per kernel (one per capacity bucket / mode /
    mesh), so the signature model must be per wrapper too: a new rcap
    bucket's first call is a REAL multi-second compile even though the
    input shapes were seen by a sibling — counting it at the name level
    only would hide exactly the silent recompiles this module exists to
    expose. Counters and the cache-entry gauge aggregate across the
    name's live wrappers (the operator's unit of reasoning)."""

    __slots__ = ("name", "compiles", "lock", "wrappers")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.lock = threading.Lock()
        self.wrappers: "weakref.WeakSet[_SigSet]" = weakref.WeakSet()

    def cache_entries(self) -> int:
        return sum(len(s) for s in self.wrappers)


def _kernel_stats(name: str) -> _KernelStats:
    with _KERNELS_LOCK:
        st = _KERNELS.get(name)
        if st is None:
            st = _KernelStats(name)
            _KERNELS[name] = st
            devstats_metrics().gauge_fn(
                f"xla.cache.{name}.entries",
                lambda s=st: s.cache_entries(),
            )
        return st


def _signature(args: tuple, kwargs: dict) -> tuple:
    """Hashable stand-in for jit's cache key: shape+dtype per array-like
    argument, the value itself for hashable statics, the type name
    otherwise. Mirrors shape-bucketed specialization exactly for the
    all-array call sites this repo has; weak-type/layout re-traces would
    undercount, never overcount."""
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            try:
                hash(a)
                sig.append(("v", a))
            except TypeError:
                sig.append(("t", type(a).__name__))
    for k in sorted(kwargs):
        sig.append((k, _signature((kwargs[k],), {})))
    return tuple(sig)


def instrumented_jit(name: str, fn, **jit_kw):
    """``jax.jit`` with compile accounting — the sanctioned jit wrapper.

    Returns a callable with the jitted function's behavior; each call
    whose argument signature THIS WRAPPER has not seen is treated as a
    compile (the model mirrors jit's per-wrapper cache — a sibling
    wrapper of the same kernel name, e.g. a new capacity bucket, pays
    its own real compiles and is counted for them): it runs inside an
    ``xla.compile`` span (attributing the stall to the query that
    triggered it), bumps the per-kernel and total compile counters, and
    records the call's wall time in the ``xla.compile`` timer (compile
    dominates first-call latency; the timer is an attribution aid, not
    a precise compiler clock). Warm calls pay one set lookup.
    """
    import jax

    jitted = jax.jit(fn, **jit_kw)
    stats = _kernel_stats(name)
    reg = devstats_metrics()
    seen = _SigSet()
    with stats.lock:
        stats.wrappers.add(seen)

    def call(*args, **kwargs):
        sig = _signature(args, kwargs)
        with stats.lock:
            fresh = sig not in seen
            if fresh:
                seen.add(sig)
                stats.compiles += 1
        if not fresh:
            return jitted(*args, **kwargs)
        reg.inc(f"xla.compile.{name}")
        reg.inc("xla.compile.total")
        _collect("recompiles", 1)
        t0 = time.perf_counter()
        with trace.span("xla.compile", kernel=name):
            out = jitted(*args, **kwargs)
        reg.update_timer("xla.compile", time.perf_counter() - t0)
        return out

    call.__name__ = f"instrumented_jit[{name}]"
    call._jitted = jitted  # escape hatch for lower()/cache introspection
    call._devstats = stats
    return call


# context-local receipt collectors: the stack of dicts that transfers
# and compiles counted from THIS thread/context also accumulate into.
# Unlike the process-wide receipt window (receipt_since), a collector is
# EXACT under concurrency — the shard coordinator (parallel/shards.py)
# wraps each per-shard scan in one so a hedged loser's bytes can never
# land in the winner's receipt.
_COLLECTORS: contextvars.ContextVar[Tuple[Dict[str, int], ...]] = (
    contextvars.ContextVar("geomesa_tpu_receipt_collectors", default=())
)
# trace.wrap copies the caller's context into worker threads, so an
# OUTER collector can legitimately be fed from several threads at once
# (e.g. collecting() around a sharded query) — the fold must not lose
# increments to interleaved read-modify-writes
_COLLECT_LOCK = threading.Lock()


@contextmanager
def collecting(out: Optional[Dict[str, int]] = None):
    """Collect this context's device costs into ``out`` (keys
    ``h2d_bytes`` / ``d2h_bytes`` / ``recompiles``), in ADDITION to the
    process-wide counters. Nests; each active collector sees every
    event. Yields the dict."""
    out = {} if out is None else out
    token = _COLLECTORS.set(_COLLECTORS.get() + (out,))
    try:
        yield out
    finally:
        _COLLECTORS.reset(token)


def _collect(key: str, n: int) -> None:
    outs = _COLLECTORS.get()
    if not outs:
        return
    with _COLLECT_LOCK:
        for out in outs:
            out[key] = out.get(key, 0) + n


def count_h2d(nbytes: int) -> None:
    """Fold one host->device transfer into the monotone byte counter
    (called from the device.dispatch boundary, parallel/mesh.py)."""
    if nbytes:
        devstats_metrics().inc("device.h2d.bytes", int(nbytes))
        _collect("h2d_bytes", int(nbytes))


def count_d2h(nbytes: int) -> None:
    """Fold one device->host transfer into the monotone byte counter
    (called from the device.fetch boundary, parallel/executor.py)."""
    if nbytes:
        devstats_metrics().inc("device.d2h.bytes", int(nbytes))
        _collect("d2h_bytes", int(nbytes))


def record_pad(rows_used: int, rows_capacity: int, kind: str = "") -> None:
    """Padding efficiency of one segment upload: real rows vs. the pow2
    capacity bucket actually dispatched. Gauges show the latest upload
    (the "is THIS mirror bloated" question); the monotone totals let a
    dashboard rate() the fleet-wide pad overhead."""
    reg = devstats_metrics()
    reg.set_gauge("device.pad.rows_used", rows_used)
    reg.set_gauge("device.pad.rows_capacity", rows_capacity)
    if rows_capacity > 0:
        reg.set_gauge("device.pad.ratio", rows_used / rows_capacity)
    # monotone upload-event count: receipts use its delta to tell "this
    # query uploaded a segment" from "the gauge is another query's"
    reg.inc("device.pad.events")
    reg.inc("device.pad.rows_used_total", int(rows_used))
    reg.inc("device.pad.rows_padded_total",
            max(0, int(rows_capacity) - int(rows_used)))
    if kind:
        trace.event("device.pad", kind=kind, used=int(rows_used),
                    capacity=int(rows_capacity))


# -- per-query cost receipt ---------------------------------------------------


_RECEIPT_COUNTERS = (
    ("recompiles", "xla.compile.total"),
    ("h2d_bytes", "device.h2d.bytes"),
    ("d2h_bytes", "device.d2h.bytes"),
    ("pad_events", "device.pad.events"),
)


def receipt_snapshot() -> Dict[str, int]:
    """Cheap point-in-time read of the receipt counters (three dict
    lookups under the registry lock — safe on the per-query hot path)."""
    reg = devstats_metrics()
    return {k: reg.counter(c) for k, c in _RECEIPT_COUNTERS}


def receipt_since(before: Dict[str, int]) -> Dict[str, Any]:
    """The per-query cost receipt: counter deltas since ``before``.
    ``pad_ratio`` reports the pad gauge only when THIS window uploaded a
    segment (the pad-event counter moved) — a warm query must not
    inherit another query's mirror efficiency — and 0.0 otherwise.
    Process-wide counters make the deltas an upper bound under
    concurrent streams, exact single-stream."""
    now = receipt_snapshot()
    out: Dict[str, Any] = {
        k: now[k] - before.get(k, 0) for k, _ in _RECEIPT_COUNTERS
    }
    uploaded = out.pop("pad_events") > 0
    out["pad_ratio"] = (
        round(devstats_metrics().gauge("device.pad.ratio"), 4)
        if uploaded else 0.0
    )
    return out


def device_debug() -> Dict[str, Any]:
    """The GET /debug/device payload: backend identity, per-kernel
    compile/cache accounting, transfer + padding counters, HBM gauges."""
    import jax

    reg = devstats_metrics()
    counters, gauges, _timers, totals = reg.snapshot()
    with _KERNELS_LOCK:
        stats = list(_KERNELS.items())
    kernels = {
        name: {
            "cache_entries": st.cache_entries(),
            "compiles": st.compiles,
        }
        for name, st in sorted(stats)
    }
    compile_count, compile_sum_s = totals.get("xla.compile", (0, 0.0))
    try:
        # lazy: the join subsystem may never have loaded in this process
        from geomesa_tpu.ops.join import join_debug

        join_block = join_debug()
    except Exception:  # noqa: BLE001 - debug page must render regardless
        join_block = {}
    try:
        # lazy: same rule for the aggregate pyramid cache
        from geomesa_tpu.ops.pyramid import agg_debug

        agg_block = agg_debug()
    except Exception:  # noqa: BLE001 - debug page must render regardless
        agg_block = {}
    try:
        backend = jax.default_backend()
        n_devices = len(jax.devices())
    except Exception as e:  # noqa: BLE001 - backend init failure is still a page
        backend = f"unavailable: {e}"
        n_devices = 0
    return {
        "backend": backend,
        "device_count": n_devices,
        "kernels": kernels,
        "compile": {
            "total": counters.get("xla.compile.total", 0),
            "wall_s": round(compile_sum_s, 4),
            "count": compile_count,
        },
        "transfer": {
            "h2d_bytes": counters.get("device.h2d.bytes", 0),
            "d2h_bytes": counters.get("device.d2h.bytes", 0),
        },
        "pad": {
            "rows_used": gauges.get("device.pad.rows_used", 0),
            "rows_capacity": gauges.get("device.pad.rows_capacity", 0),
            "ratio": gauges.get("device.pad.ratio", 0.0),
            "rows_used_total": counters.get("device.pad.rows_used_total", 0),
            "rows_padded_total": counters.get(
                "device.pad.rows_padded_total", 0
            ),
        },
        "hbm": {
            "live_bytes": gauges.get("device.hbm.live_bytes", 0),
            "bytes_in_use": gauges.get("device.hbm.bytes_in_use", 0),
            "peak_bytes_in_use": gauges.get(
                "device.hbm.peak_bytes_in_use", 0
            ),
        },
        # spatial-join telemetry (ops/join.py): build-cache occupancy +
        # hit/miss counters, bucket skew histogram, split/pair counters
        "join": join_block,
        # aggregate pyramid cache (ops/pyramid.py): entries/bytes,
        # hit/miss/build/eviction counters, latest pyramid shape
        "agg": agg_block,
        # cross-query coalescer reach (parallel/batch.py admission
        # groups + parallel/executor.dispatch_coalesced routing): how
        # many groups formed, the pow2 group-size histogram (all-1s
        # means the window never fills), how many member plans rode a
        # stacked-mask sweep vs fell to the dispatch_many batch paths,
        # and the mesh size the sweeps compiled for — the timeline/SLO
        # layer's "is the coalescer earning its window" signal
        "coalesce": {
            "groups": counters.get("batch.coalesce.groups", 0),
            "members": counters.get("batch.coalesce.members", 0),
            "stacked_plans": counters.get("batch.coalesce.plans.stacked", 0),
            "rest_plans": counters.get("batch.coalesce.plans.rest", 0),
            "devices": gauges.get("batch.coalesce.devices", 0),
            # NUMERIC bucket order: lexical sort would interleave 16/32
            # between 1 and 2, scrambling exactly the large-group tail
            # the histogram exists to show
            "group_pow2": {
                k.rsplit(".", 1)[1]: counters[k]
                for k in sorted(
                    (
                        k for k in counters
                        if k.startswith("batch.coalesce.group.pow2.")
                    ),
                    key=lambda k: int(k.rsplit(".", 1)[1]),
                )
            },
        },
    }
