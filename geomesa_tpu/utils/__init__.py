"""Foundation utilities (the geomesa-utils analogs not already absorbed by
other layers): geohash math, audit events, metrics registry, profiling."""

import datetime as _dt


def fmt_instant_ms(ms: int) -> str:
    """Epoch-ms -> ISO-8601 UTC with millisecond precision (the one
    formatter CQL serialization and the CLI listen tail share)."""
    dt = _dt.datetime.fromtimestamp(ms / 1000.0, tz=_dt.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"
