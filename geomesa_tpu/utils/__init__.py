"""Foundation utilities (the geomesa-utils analogs not already absorbed by
other layers): geohash math, audit events, metrics registry, profiling."""
