"""Deterministic fault injection at I/O and device boundaries.

The production posture (ROADMAP.md) needs the failure paths exercised as
routinely as the happy paths: the reference inherits retry/recovery from
Accumulo and Kafka, and this rebuild replaced those substrates, so every
recovery behavior here must be proved by injection rather than assumed.
Named fault points instrument each place the system crosses a process,
disk, or device boundary:

    fs.block_read      columnar block deserialization (store/fs.py)
    fs.block_write     columnar block persistence (store/fs.py, blobstore)
    fs.block_delete    journaled file deletion (store/journal.py)
    metadata.save      schema-registry flush (store/metadata.py)
    journal.intent     intent-record publish (store/journal.py)
    journal.commit     intent-record commit/unlink (store/journal.py)
    netlog.rpc         RemoteLogBroker request/response (stream/netlog.py)
    broker.poll        log-broker record fetch (stream/filelog.py, broker.py)
    device.dispatch    host->device placement (parallel/mesh.py)
    device.fetch       device->host result resolution (parallel/executor.py)
    shard.rpc          coordinator->shard scan scatter (parallel/shards.py);
                       a ``crash`` here simulates the SHARD process dying —
                       the coordinator observes it as a dead peer and fails
                       over to a replica placement
    shard.merge        shard-result gather/merge (parallel/shards.py)
    join.build         build-side bucketing + device upload (ops/join.py)
    join.probe         per-chunk probe dispatch of a spatial join
                       (ops/join.py); device failures here degrade to
                       the host reference join with identical pairs
    batch.coalesce     the cross-query coalescing seam (parallel/batch.py):
                       the shared plan+dispatch phase a group leader runs
                       for every coalesced member. A failure here degrades
                       the WHOLE group to per-query solo execution with
                       identical results — one member's fault never fails
                       a sibling
    fleet.rpc          coordinator->worker-process RPC (parallel/fleet.py):
                       the cross-process edition of shard.rpc — one
                       request/response exchange with a spawned shard
                       worker; a ``crash`` here models the WORKER process
                       dying mid-exchange (the coordinator fails over,
                       like shard.rpc), and error/drop model the transport
    fleet.rpc.send     the coordinator->worker DIRECTION of a fleet RPC:
                       a rule here fires before the request leaves the
                       coordinator, so a ``drop`` schedule models an
                       asymmetric network partition where requests (and
                       heartbeat pings) never reach the worker while its
                       replies would still flow. ``fleet.rpc`` rules keep
                       matching both directions; ``fleet.rpc.*`` wildcards
                       match the directional points only
    fleet.rpc.recv     the worker->coordinator DIRECTION: fires after the
                       worker has processed the request, before the
                       coordinator reads the reply — a ``drop`` models the
                       asymmetric partition where a mutation APPLIED but
                       its ack was lost (the idempotent-apply/dedupe
                       machinery must absorb the retry)
    fleet.launch       one worker launch through the WorkerLauncher SPI
                       (parallel/launch.py): process start + endpoint
                       handshake, bounded by geomesa.fleet.spawn.timeout —
                       an ``error`` exercises the supervisor's restart
                       ladder, a ``crash`` models the coordinator dying
                       mid-launch
    fleet.ship         one chunk position of a streamed partition ship
                       (parallel/fleet.py): the chunked source->target
                       replica copy behind rebalance/repair — a ``crash``
                       at ANY chunk position must leave a state the next
                       repair pass completes idempotently (dirty-mark
                       obligation + journaled ship record), never a
                       duplicated or half-visible row
    fleet.heartbeat    one supervisor heartbeat probe (parallel/fleet.py):
                       faults here exercise the missed-beat -> suspect ->
                       dead membership machine without touching a real
                       process
    fleet.rebalance    one placement move (parallel/fleet.py): partition
                       primary reassignment on worker join/leave/death,
                       journaled through the fleet intent journal — a
                       ``crash`` at any position must recover to exactly
                       the pre- or post-move placement, never a partition
                       owned by zero or two primaries
    fleet.lease        one coordinator lease acquire/renew (parallel/
                       fleet.py): the durably-leased ``_fleet/lease``
                       file with its fencing epoch — a ``crash`` here
                       models the ACTIVE COORDINATOR dying between
                       renewals; the standby must take over past the
                       TTL with a higher epoch, and the zombie's
                       stale-epoch mutating RPCs must bounce at the
                       workers (split-brain fencing)
    fleet.fanout       one cross-worker mutation fan-out position
                       (parallel/fleet.py): delete/compact/
                       delete_schema/age_off journal a roll-forward
                       fan-out intent (participants + per-worker
                       done-marks) before touching any worker — a
                       ``crash`` at any position replays the remaining
                       participants at takeover/restart, never leaving
                       half the workers mutated
    history.append     one write-behind flush of the durable telemetry
                       spool (utils/history.py): the sampler-tick
                       thread appending queued records to the active
                       ``_telemetry`` segment — an ``error``/``drop``
                       here must re-queue (never lose silently, never
                       block a query), overflow past the bounded queue
                       counts ``history.dropped``
    workload.append    one write-behind flush of the workload-capture
                       spool (utils/workload.py): the sampler-tick
                       thread appending queued query descriptors to the
                       active ``wl-*`` segment — an ``error``/``drop``
                       here must re-queue (never lose silently, never
                       perturb a query), overflow past the bounded
                       queue counts ``workload.dropped``

Kinds:

    error      raise InjectedFault (an OSError: retry policies treat it
               as transient, exactly like a real EIO)
    drop       raise InjectedDrop (a ConnectionError: a peer hanging up
               mid-exchange)
    latency    sleep a few milliseconds before proceeding
    torn       truncate a just-written file before it is published
               (``maybe_tear``) — the crash-between-write-and-rename
               window the fsync fixes close for real crashes
    crash      raise SimulatedCrash (a BaseException): the process dies
               HERE — no retry classifies it, no except-Exception
               recovery path absorbs it, cleanup handlers written as
               ``except Exception`` (not ``finally``) are skipped, so
               disk is left exactly as a SIGKILL would leave it. The
               crash harness (tests/test_crash.py) catches it at top
               level and reopens the store from disk, proving startup
               recovery (store/journal.py) restores pre- or post-state.

Activation is either environment-driven::

    GEOMESA_FAULTS="fs.block_read:error=0.1,netlog.rpc:drop=0.05"
    GEOMESA_FAULTS_SEED=42

Spec rules may position themselves deterministically with an ``@`` suffix
on the kind: ``point:kind@S=prob`` skips the first S times the rule would
fire, and ``point:kind@SxM`` additionally caps it at M fires — so
``shard.rpc:latency@2x1`` slows exactly the third shard scan and nothing
else (the deterministic-hedge-test schedule), the spec-string form of
``FaultRule(skip=2, max_fires=1)``. Positioning works for EVERY kind,
not just crash (the crash harness's original use).

or programmatic and scoped::

    with faults.inject("device.fetch:error=0.5", seed=7):
        store.query("t", "bbox(geom, 0, 0, 10, 10)")

Draws come from a ``random.Random`` seeded per activation, so a chaos
soak replays the same fault schedule from the same seed (single-threaded
call order assumed; concurrent callers serialize on the set's lock but
interleave nondeterministically). Every fired fault is counted in
``utils.audit.robustness_metrics()`` under ``fault.<point>.<kind>``.

With no active rules (the common case) ``fault_point`` is one env read
and a list check — cheap enough to sit on every block read.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from geomesa_tpu.utils import trace
from geomesa_tpu.utils.audit import robustness_metrics

FAULT_POINTS = (
    "fs.block_read",
    "fs.block_write",
    "fs.block_delete",
    "metadata.save",
    "journal.intent",
    "journal.commit",
    "netlog.rpc",
    "broker.poll",
    "device.dispatch",
    "device.fetch",
    "shard.rpc",
    "shard.merge",
    "join.build",
    "join.probe",
    "agg.build",
    "batch.coalesce",
    "fleet.rpc",
    "fleet.rpc.send",
    "fleet.rpc.recv",
    "fleet.heartbeat",
    "fleet.rebalance",
    "fleet.lease",
    "fleet.fanout",
    "fleet.launch",
    "fleet.ship",
    "history.append",
    "workload.append",
)

KINDS = ("error", "drop", "latency", "torn", "crash")


class InjectedFault(OSError):
    """An ``error`` rule fired. OSError, so I/O retry policies classify
    it as transient — the same treatment a real EIO would get."""


class InjectedDrop(ConnectionError):
    """A ``drop`` rule fired: the peer hung up mid-exchange."""


class SimulatedCrash(BaseException):
    """A ``crash`` rule fired: the process "dies" here. Deliberately a
    BaseException — retry policies and except-Exception degradation
    paths must NOT absorb it, and ``except Exception`` tmp-cleanup
    handlers must not run, so the unwind leaves disk exactly as a real
    crash would. Only the crash harness catches it."""


@dataclass
class FaultRule:
    """One injection rule. ``point`` is an exact fault-point name or a
    prefix ending in ``*`` (``fs.*`` matches the fs points).
    ``max_fires`` bounds how many times the rule may fire (a schedule of
    "the first two reads fail" is ``prob=1, max_fires=2``); ``skip``
    suppresses the first k times the rule would otherwise fire — generic
    Nth-hit positioning for ANY kind: "crash at the k-th block write" is
    ``kind="crash", max_fires=1, skip=k`` (the crash harness sweeps k to
    walk a crash point through an op), and "slow exactly the third shard
    scan" is ``kind="latency", max_fires=1, skip=2`` (the deterministic
    hedge-test schedule). Spec-string form: ``point:kind@skip[xfires]``."""

    point: str
    kind: str
    prob: float = 1.0
    latency_s: float = 0.002
    max_fires: Optional[int] = None
    skip: int = 0
    fired: int = 0
    seen: int = 0

    def matches(self, point: str) -> bool:
        if self.point.endswith("*"):
            return point.startswith(self.point[:-1])
        return self.point == point


class FaultSet:
    """One activation of fault rules with its own seeded RNG. Use as a
    context manager for scoped injection; the env-derived set stays
    active for the whole process."""

    def __init__(self, rules, seed: Optional[int] = None):
        for r in rules:
            if r.kind not in KINDS:
                raise ValueError(f"unknown fault kind {r.kind!r} (kinds: {KINDS})")
        self.rules: List[FaultRule] = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def draw(self, point: str, kinds) -> Optional[FaultRule]:
        """First matching rule that fires for ``point``, or None. The RNG
        draw and fire bookkeeping serialize (broker handler threads hit
        points concurrently with client threads)."""
        with self._lock:
            for rule in self.rules:
                if rule.kind not in kinds or not rule.matches(point):
                    continue
                if rule.max_fires is not None and rule.fired >= rule.max_fires:
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                rule.seen += 1
                if rule.seen <= rule.skip:
                    continue
                rule.fired += 1
                return rule
        return None

    def __enter__(self) -> "FaultSet":
        with _STACK_LOCK:
            _STACK.append(self)
        return self

    def __exit__(self, *exc) -> None:
        with _STACK_LOCK:
            try:
                _STACK.remove(self)
            except ValueError:
                pass


def parse(spec: str, seed: Optional[int] = None) -> FaultSet:
    """``"<point>:<kind>[@skip[xfires]][=<prob>],..."`` -> FaultSet.
    ``=<prob>`` is optional (default 1.0); ``@skip`` positions the rule
    at the (skip+1)-th hit, ``xfires`` caps total fires — e.g.
    ``shard.rpc:latency@2x1`` fires once, on exactly the third hit."""
    rules = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        pk, _, prob = part.partition("=")
        point, sep, kind = pk.partition(":")
        if not sep:
            raise ValueError(
                f"bad fault spec {part!r} (want point:kind[@skip[xfires]][=prob])"
            )
        kind, _, pos = kind.partition("@")
        skip, max_fires = 0, None
        if pos:
            skip_s, _, fires_s = pos.partition("x")
            try:
                skip = int(skip_s)
                if fires_s:
                    max_fires = int(fires_s)
            except ValueError:
                raise ValueError(
                    f"bad fault position {pos!r} in {part!r} (want @skip[xfires])"
                ) from None
        rules.append(
            FaultRule(
                point.strip(), kind.strip(), float(prob) if prob else 1.0,
                max_fires=max_fires, skip=skip,
            )
        )
    return FaultSet(rules, seed=seed)


def inject(spec: Optional[str] = None, *, rules=None, seed: Optional[int] = None) -> FaultSet:
    """Programmatic scoped activation::

        with faults.inject("fs.block_read:error=0.2", seed=3): ...
        with faults.inject(rules=[FaultRule("netlog.rpc", "drop", max_fires=1)]): ...
    """
    if (spec is None) == (rules is None):
        raise ValueError("pass exactly one of spec= or rules=")
    return parse(spec, seed=seed) if spec is not None else FaultSet(rules, seed=seed)


_STACK: List[FaultSet] = []
_STACK_LOCK = threading.Lock()
# (env spec string, parsed set): re-parsed only when GEOMESA_FAULTS changes
_ENV_CACHE: Tuple[Optional[str], Optional[FaultSet]] = (None, None)


def _env_set() -> Optional[FaultSet]:
    global _ENV_CACHE
    spec = os.environ.get("GEOMESA_FAULTS")
    cached_spec, cached = _ENV_CACHE
    if spec != cached_spec:
        seed_s = os.environ.get("GEOMESA_FAULTS_SEED")
        cached = (
            parse(spec, seed=None if seed_s is None else int(seed_s))
            if spec
            else None
        )
        _ENV_CACHE = (spec, cached)
    return cached


def _active_sets() -> List[FaultSet]:
    env = _env_set()
    if not _STACK:
        return [env] if env is not None else []
    with _STACK_LOCK:
        stack = list(_STACK)
    return ([env] if env is not None else []) + stack


def fault_point(point: str, direction: Optional[str] = None) -> None:
    """The harness hook: call at a named boundary. ``error``/``drop``/
    ``crash`` rules raise, ``latency`` sleeps; ``torn`` rules are
    write-site only (see ``maybe_tear``) and never fire here.

    ``direction`` narrows the draw to the directional sub-point
    ``<point>.<direction>`` (e.g. ``fleet.rpc`` + ``send`` draws only
    ``fleet.rpc.send`` rules), so a schedule can drop one direction of
    a duplex boundary while the other keeps flowing — an asymmetric
    network partition. A directional call deliberately does NOT re-draw
    the bare point's rules: the bare call at the same boundary already
    fired them once, and firing twice would double a probability
    schedule."""
    if direction is not None:
        point = f"{point}.{direction}"
    for fs in _active_sets():
        rule = fs.draw(point, ("error", "drop", "latency", "crash"))
        if rule is None:
            continue
        robustness_metrics().inc(f"fault.{point}.{rule.kind}")
        # per-query attribution: the fired fault lands as an event on the
        # affected query's span tree, joining the process-wide fault.*
        # counters to the trace that suffered the injection
        trace.event(f"fault.{point}.{rule.kind}")
        if rule.kind == "latency":
            # a latency fault never sleeps past the ambient query budget:
            # the next deadline.check at this boundary fires, so a
            # latency schedule costs at most deadline + one granularity
            from geomesa_tpu.utils import deadline as _deadline

            left = _deadline.remaining()
            time.sleep(
                rule.latency_s
                if left is None
                else max(0.0, min(rule.latency_s, left))
            )
        elif rule.kind == "drop":
            raise InjectedDrop(f"injected connection drop at {point}")
        elif rule.kind == "crash":
            raise SimulatedCrash(f"simulated crash at {point}")
        else:
            raise InjectedFault(f"injected error at {point}")


def maybe_tear(point: str, path: str) -> bool:
    """Apply a fired ``torn`` rule to a just-written (not yet published)
    file: truncate it to half, returning True. The caller publishes the
    torn file anyway — simulating a crash inside the write-then-rename
    window so the corruption-detection/quarantine path stays provable
    even though the fsync fixes close that window for real crashes."""
    for fs in _active_sets():
        rule = fs.draw(point, ("torn",))
        if rule is None:
            continue
        robustness_metrics().inc(f"fault.{point}.torn")
        trace.event(f"fault.{point}.torn", path=path)
        size = os.path.getsize(path)
        with open(path, "rb+") as fh:
            fh.truncate(max(0, size // 2))
        return True
    return False
