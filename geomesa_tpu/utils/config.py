"""Tiered runtime configuration (GeoMesaSystemProperties analog).

Reference (geomesa-utils conf/GeoMesaSystemProperties.scala:17-80): each
knob is a named SystemProperty resolved through tiers — config file value
(optionally final), then JVM system properties, then the default. Here the
tiers are: programmatic overrides (set_property / properties context
manager), then environment variables (dots become underscores, upper-cased,
e.g. ``geomesa.scan.ranges.target`` -> ``GEOMESA_SCAN_RANGES_TARGET``),
then the default. Duration/bytes parsing mirrors toDuration/toBytes.
"""

from __future__ import annotations

import os
import re
import threading
from contextlib import contextmanager
from typing import Dict, Optional

_overrides: Dict[str, str] = {}
_lock = threading.Lock()

# every SystemProperty ever constructed, by name (last construction
# wins a name collision — module reloads in tests). The incident-report
# bundle (web.py GET /debug/report) snapshots this: "what was every knob
# resolved to WHEN the pager fired" is the config half of any incident.
_KNOWN: Dict[str, "SystemProperty"] = {}


def config_snapshot() -> Dict[str, Optional[str]]:
    """Every known knob's CURRENTLY-RESOLVED value (override -> env ->
    default), sorted by name. A point-in-time read — cheap enough for
    the /debug/report bundle, never cached."""
    return {name: _KNOWN[name].get() for name in sorted(_KNOWN)}


def set_property(name: str, value: Optional[str]) -> None:
    """Set (or clear, with None) a programmatic override — the top tier."""
    with _lock:
        if value is None:
            _overrides.pop(name, None)
        else:
            _overrides[name] = str(value)


@contextmanager
def properties(**kwargs):
    """Scoped overrides: properties(geomesa_query_timeout=\"10 seconds\")
    — underscores in keyword names map to dots."""
    names = {k.replace("_", "."): v for k, v in kwargs.items()}
    before = {n: _overrides.get(n) for n in names}
    for n, v in names.items():
        set_property(n, v)
    try:
        yield
    finally:
        for n, v in before.items():
            set_property(n, v)


_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]+)\s*$")
_DURATION_MS = {
    "ms": 1, "millis": 1, "millisecond": 1, "milliseconds": 1,
    "s": 1000, "second": 1000, "seconds": 1000,
    "m": 60_000, "min": 60_000, "minute": 60_000, "minutes": 60_000,
    "h": 3_600_000, "hour": 3_600_000, "hours": 3_600_000,
    "d": 86_400_000, "day": 86_400_000, "days": 86_400_000,
}
_BYTES = {"b": 1, "k": 1024, "kb": 1024, "m": 1024**2, "mb": 1024**2,
          "g": 1024**3, "gb": 1024**3, "t": 1024**4, "tb": 1024**4}


class SystemProperty:
    """One named knob; resolution happens on every get (values can change
    under tests / long-running processes, like the reference's sys-props)."""

    def __init__(self, name: str, default: Optional[str] = None):
        self.name = name
        self.default = default
        _KNOWN[name] = self  # GIL-atomic; last construction wins

    def get(self) -> Optional[str]:
        with _lock:
            if self.name in _overrides:
                return _overrides[self.name]
        env = os.environ.get(self.name.replace(".", "_").upper())
        if env is not None:
            return env
        return self.default

    def to_int(self) -> Optional[int]:
        v = self.get()
        try:
            return None if v is None else int(v)
        except ValueError:
            return None if self.default is None else int(self.default)

    def to_float(self) -> Optional[float]:
        v = self.get()
        try:
            return None if v is None else float(v)
        except ValueError:
            return None if self.default is None else float(self.default)

    def to_bool(self) -> Optional[bool]:
        v = self.get()
        return None if v is None else v.strip().lower() in ("true", "1", "yes")

    def to_duration_ms(self) -> Optional[int]:
        """'10 seconds' / '5m' / '100 ms' -> milliseconds."""
        for v in (self.get(), self.default):
            if v is None:
                continue
            m = _DURATION_RE.match(str(v))
            if m and m.group(2).lower() in _DURATION_MS:
                return int(float(m.group(1)) * _DURATION_MS[m.group(2).lower()])
            try:
                return int(v)  # bare number = ms
            except ValueError:
                continue
        return None

    def to_duration_s(self, default_s: Optional[float] = None) -> Optional[float]:
        """``to_duration_ms`` in SECONDS, with a caller default — the one
        home for the ms->s conversion every timeout knob consumer needs
        (breakers, socket timeouts, query budgets)."""
        ms = self.to_duration_ms()
        return default_s if ms is None else ms / 1000.0

    def to_bytes(self) -> Optional[int]:
        for v in (self.get(), self.default):
            if v is None:
                continue
            m = re.match(r"^\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*$", str(v))
            if m and (m.group(2) or "b").lower() in _BYTES:
                return int(float(m.group(1)) * _BYTES[(m.group(2) or "b").lower()])
        return None


# the reference's commonly-tuned knobs (QueryProperties.scala analogs).
# The range budget defaults to 512, NOT the reference's 2000
# (QueryProperties.scala:18): with the one-pass native seek-scan, extra
# candidate rows from coarser cells cost ~ns each while every extra range
# costs planning + searchsorted work — 512 is the measured sweet spot for
# this execution model. Set the property/env to 2000 for reference parity.
SCAN_RANGES_TARGET = SystemProperty("geomesa.scan.ranges.target", "512")
QUERY_TIMEOUT = SystemProperty("geomesa.query.timeout", None)
# Overload protection (utils/admission.py): at most max.inflight queries
# execute concurrently per store; queue.depth more may wait (the wait
# charged against each query's own deadline); beyond that, ShedLoad —
# a fast 503 instead of queueing into collapse.
QUERY_MAX_INFLIGHT = SystemProperty("geomesa.query.max.inflight", "64")
QUERY_QUEUE_DEPTH = SystemProperty("geomesa.query.queue.depth", "256")
# Circuit breakers (utils/breaker.py): trip open after `failures`
# boundary failures inside `window`, short-circuit for `cooldown`, then
# let one probe through.
BREAKER_FAILURES = SystemProperty("geomesa.breaker.failures", "5")
BREAKER_WINDOW = SystemProperty("geomesa.breaker.window", "30 seconds")
BREAKER_COOLDOWN = SystemProperty("geomesa.breaker.cooldown", "5 seconds")
# Sharded scatter/gather (parallel/shards.py): the coordinator fans a
# query out over `count` shard workers, each partition written to its
# primary + `replicas` successor shards. Each per-shard scan gets
# `deadline.fraction` of the query's REMAINING budget (the slice leaves
# room for a hedge/failover inside the same overall deadline); a shard
# lagging past the `hedge.quantile` of its completed siblings (and past
# `hedge.min.ms` — the floor keeps microsecond jitter from hedging
# everything) is re-issued to a replica, first answer wins. Per-shard
# admission rides `max.inflight`/`queue.depth` (the per-process PR 4
# knobs become a per-shard budget).
SHARD_COUNT = SystemProperty("geomesa.shard.count", "4")
SHARD_REPLICAS = SystemProperty("geomesa.shard.replicas", "1")
SHARD_HEDGE_QUANTILE = SystemProperty("geomesa.shard.hedge.quantile", "0.9")
SHARD_HEDGE_MIN_MS = SystemProperty("geomesa.shard.hedge.min.ms", "25")
SHARD_DEADLINE_FRACTION = SystemProperty("geomesa.shard.deadline.fraction", "0.5")
SHARD_MAX_INFLIGHT = SystemProperty("geomesa.shard.max.inflight", "32")
SHARD_QUEUE_DEPTH = SystemProperty("geomesa.shard.queue.depth", "128")
# Multi-host serving tier (parallel/fleet.py): the FleetDataStore
# coordinator runs each shard as a SPAWNED WORKER PROCESS owning its
# partitions' FsDataStore roots, supervised by a heartbeat membership
# loop. `workers` overrides geomesa.shard.count for the fleet; a worker
# missing `heartbeat.suspect` consecutive beats (one per
# `heartbeat.interval`) is SUSPECT (no action — hysteresis, so one slow
# GC pause never triggers a partition move), `heartbeat.dead` misses is
# DEAD: its primary partitions move to live replicas (journaled through
# the fleet intent journal) and the supervisor restarts the process
# with bounded exponential backoff (`restart.base`..`restart.cap`, at
# most `restart.max` attempts per death). A worker dying more than
# `flap.restarts` times inside `flap.window` is marked OUT via its
# shard.<n> breaker instead of being restarted again. `drain.timeout`
# bounds graceful drain (in-flight scans complete against their own
# deadlines; new admissions shed to the successor). `rpc.timeout` is
# the per-attempt socket budget of every fleet RPC, always re-clamped
# to the calling query's remaining deadline; `spawn.timeout` bounds how
# long a spawned worker may take to publish its port.
FLEET_WORKERS = SystemProperty("geomesa.fleet.workers", None)
FLEET_HEARTBEAT_INTERVAL = SystemProperty(
    "geomesa.fleet.heartbeat.interval", "1 second"
)
FLEET_HEARTBEAT_SUSPECT = SystemProperty("geomesa.fleet.heartbeat.suspect", "2")
FLEET_HEARTBEAT_DEAD = SystemProperty("geomesa.fleet.heartbeat.dead", "4")
FLEET_RESTART_BASE = SystemProperty("geomesa.fleet.restart.base", "200 ms")
FLEET_RESTART_CAP = SystemProperty("geomesa.fleet.restart.cap", "5 seconds")
FLEET_RESTART_MAX = SystemProperty("geomesa.fleet.restart.max", "6")
FLEET_FLAP_RESTARTS = SystemProperty("geomesa.fleet.flap.restarts", "3")
FLEET_FLAP_WINDOW = SystemProperty("geomesa.fleet.flap.window", "60 seconds")
FLEET_DRAIN_TIMEOUT = SystemProperty("geomesa.fleet.drain.timeout", "10 seconds")
FLEET_RPC_TIMEOUT = SystemProperty("geomesa.fleet.rpc.timeout", "10 seconds")
FLEET_SPAWN_TIMEOUT = SystemProperty("geomesa.fleet.spawn.timeout", "30 seconds")
# fleet observability: cross-process trace stitching (worker span
# subtrees return in a bounded reply trailer and graft under the
# coordinator's fleet.rpc span) and the fleet debug plane's passive
# observation budget (telemetry/timeline/debug/plans RPCs — a wedged
# worker costs a probe at most this, never the rpc.timeout x retries)
FLEET_TRACE_STITCH = SystemProperty("geomesa.fleet.trace.stitch", "true")
FLEET_TRACE_MAX_BYTES = SystemProperty(
    "geomesa.fleet.trace.max.bytes", "262144"
)
FLEET_DEBUG_BUDGET = SystemProperty("geomesa.fleet.debug.budget", "1 second")
FLEET_DEBUG_TRACES = SystemProperty("geomesa.fleet.debug.traces", "16")
# Coordinator HA (parallel/fleet.py): the active coordinator holds the
# durably-leased `_fleet/lease` file (fencing epoch bumped on every
# acquire), renewing it every `lease.renew.interval`; a standby
# coordinator watching the same root takes over once the lease has gone
# `lease.ttl` without a renewal. Workers remember the highest epoch
# they have served and reject mutating RPCs carrying an older one, so a
# fenced-out zombie coordinator can never split-brain a write.
# `scan.chunk.bytes` bounds each Arrow frame of a streamed worker scan
# reply (op_scan chunks through `iter_column_chunks` with the deadline
# checked per chunk); explicit 0 disables streaming and restores the
# materialize-then-reply exchange.
FLEET_LEASE_TTL = SystemProperty("geomesa.fleet.lease.ttl", "3 seconds")
FLEET_LEASE_RENEW = SystemProperty(
    "geomesa.fleet.lease.renew.interval", "1 second"
)
FLEET_SCAN_CHUNK_BYTES = SystemProperty(
    "geomesa.fleet.scan.chunk.bytes", "8MB"
)
# Remote-ready fleet (parallel/launch.py + the ship protocol in
# parallel/fleet.py). `launcher` selects the WorkerLauncher the
# supervisor routes EVERY process-lifecycle action through (first
# launch, restart ladder, takeover adoption, kill): `local` is the
# in-tree Popen + portfile handshake, `ssh` renders `ssh.command` — a
# shell template with {python} {id} {root} {host} placeholders — and
# reads the worker's `ENDPOINT host:port` announcement from the remote
# stdout (the portfile is a LOCAL launcher detail, not the contract).
# `ship.chunk.bytes` bounds each Arrow frame of a streamed partition
# ship (source->target replica copy); unset inherits scan.chunk.bytes,
# explicit 0 disables streaming and restores the materialized copy.
# `fence.ttl` is the worker-side self-fencing window: a worker
# whose observed lease epoch has not been refreshed (by a heartbeat
# ping or a mutating RPC) for longer than this rejects same-epoch
# mutating RPCs with StaleEpoch — reads keep serving — until a
# heartbeat or a higher epoch proves the coordinator is live again;
# unset inherits geomesa.fleet.lease.ttl.
FLEET_LAUNCHER = SystemProperty("geomesa.fleet.launcher", "local")
FLEET_SSH_COMMAND = SystemProperty("geomesa.fleet.ssh.command", None)
FLEET_SHIP_CHUNK_BYTES = SystemProperty(
    "geomesa.fleet.ship.chunk.bytes", None
)
FLEET_FENCE_TTL = SystemProperty("geomesa.fleet.fence.ttl", None)
# Spatial placement granularity: partitions are low-resolution z2 cells
# of the point geometry (store/partitions.Z2Scheme, `bits` even), so a
# bbox query routes to the shards owning intersecting cells only;
# schemas without a point geometry fall back to fid-hash partitions.
SHARD_PARTITION_BITS = SystemProperty("geomesa.shard.partition.bits", "4")
# Device-side spatial joins (ops/join.py): the build side buckets into a
# low-resolution z2 grid (2^bits x 2^bits base cells); any bucket holding
# more than `skew.threshold` geometries quad-splits into finer cells
# (up to `split.depth` extra levels) so one hot geofence cluster cannot
# blow the pow2 pad budget of every kernel dispatch. Built build sides
# stay HBM-resident keyed by schema generation for `cache.ttl`; probe
# points stream through the segment-upload path `probe.chunk` rows at a
# time (padded to the pow2 bucket above the chunk).
JOIN_BUCKET_BITS = SystemProperty("geomesa.join.bucket.bits", "3")
JOIN_SKEW_THRESHOLD = SystemProperty("geomesa.join.skew.threshold", "128")
JOIN_SPLIT_DEPTH = SystemProperty("geomesa.join.split.depth", "6")
JOIN_CACHE_TTL = SystemProperty("geomesa.join.cache.ttl", "10 minutes")
JOIN_PROBE_CHUNK = SystemProperty("geomesa.join.probe.chunk", "2048")
# Aggregate pyramid cache (ops/pyramid.py): per-type z2-gridded partial
# aggregates (count, per-column sum/min/max) answering hot count/stats
# aggregations from interior partial sums with an exact boundary-ring
# fallthrough, plus a density-grid query memo. `cell.bits` sets the
# finest level's grid (2^bits x 2^bits cells over the world); `levels`
# stacks that many coarser halvings above it (the hierarchical descent
# the polygon classifier walks). Entries are TTL'd per LAST USE and the
# cache is bounded by `cache.bytes` (LRU past it); device copies are
# evicted with their entry so idle pyramids release HBM.
AGG_ENABLED = SystemProperty("geomesa.agg.enabled", "true")
AGG_LEVELS = SystemProperty("geomesa.agg.levels", "3")
AGG_CELL_BITS = SystemProperty("geomesa.agg.cell.bits", "8")
AGG_CACHE_TTL = SystemProperty("geomesa.agg.cache.ttl", "10 minutes")
AGG_CACHE_BYTES = SystemProperty("geomesa.agg.cache.bytes", "64MB")
# Cross-query coalescing (parallel/batch.py): concurrently admitted
# queries of one feature type gather for up to `window.ms` (cap
# `max.queries` members), stack their compiled predicate parameters
# into ONE batched device sweep ([N, rows] mask), and demux per query —
# per-query results, per-query audit rows, receipts split with the
# shared sweep cost apportioned. Runs strictly AFTER admission (ShedLoad
# semantics unchanged); every member keeps its own deadline (a budget
# that dies mid-window ejects crisply with QueryTimeout). `enabled=0`
# is the escape hatch: the solo path answers identically. The window
# only opens when another query is in flight or a group is already
# gathering, so an unsaturated store pays zero added latency.
BATCH_ENABLED = SystemProperty("geomesa.batch.enabled", "true")
BATCH_WINDOW_MS = SystemProperty("geomesa.batch.window.ms", "2")
BATCH_MAX_QUERIES = SystemProperty("geomesa.batch.max.queries", "32")
# Multi-chip coalescing: on an SPMD mesh a coalesced group compiles to
# ONE collective-free stacked-mask sweep per chip (shard_map over the
# segment mirrors). `spmd.enabled=0` declines every coalesced plan to
# the dispatch_many batch paths instead (per-plan reason-coded
# `coalesce/spmd_disabled`), identical answers — the A/B lever for the
# SPMD kernel itself; single-device meshes ignore it.
BATCH_SPMD_ENABLED = SystemProperty("geomesa.batch.spmd.enabled", "true")
# Streaming result delivery (TpuDataStore.query_stream / web.py
# GET /query?stream=1, POST /query/stream): per-block Arrow record
# batches flush as scanning progresses; `batch.rows` caps the rows per
# emitted RecordBatch (a huge block still streams in bounded frames).
STREAM_BATCH_ROWS = SystemProperty("geomesa.stream.batch.rows", "8192")
# Sharded streaming (ShardedDataStore.query_stream): per-shard partial
# Arrow batches flush as each shard group's outcome becomes FINAL (a
# success can no longer be rolled back by failover), instead of
# gather-then-chunk; any late shard failure still ends the stream
# crisply before the terminating chunk. `incremental=0` restores the
# materialize-then-chunk posture (identical answers, no first-byte win).
STREAM_SHARD_INCREMENTAL = SystemProperty(
    "geomesa.stream.shard.incremental", "true"
)
# Socket-timeout knobs: NO I/O boundary is unbounded-by-default. The
# netlog RPC client derives its per-attempt timeout from
# min(geomesa.netlog.timeout, the query's remaining deadline); auxiliary
# sockets (graphite reporter, RESP enrichment cache) use
# geomesa.socket.timeout.
NETLOG_TIMEOUT = SystemProperty("geomesa.netlog.timeout", "30 seconds")
SOCKET_TIMEOUT = SystemProperty("geomesa.socket.timeout", "10 seconds")
# Slow-query budget: any query slower than this logs its FULL span tree
# plus the plan explain (the audit-log "why was this one slow" answer;
# duration string, e.g. '500 ms'). Unset = no slow-query log.
SLOW_QUERY_THRESHOLD = SystemProperty("geomesa.query.slow.threshold", None)
# Slow-log storm guard: at most this many FULL slow-query log emissions
# (span tree + explain render) per minute; entries past the budget still
# land in the bounded in-memory tail (utils/audit.slow_query_tail — the
# /debug/report section) as a cheap summary, counted under
# `slowlog.dropped`. An overload event must not turn the observability
# layer into the bottleneck it is measuring.
SLOW_QUERY_MAX_PER_MIN = SystemProperty("geomesa.query.slow.max.per.min", "60")
# Flight-recorder telemetry timeline (utils/timeline.py): a daemon
# thread samples every registry counter/gauge/timer, breaker states,
# admission depth, and device stats once per `interval` into a
# fixed-memory ring covering `window` — the "what changed in the last
# 60 seconds" answer behind GET /debug/timeline. `enabled=0` starts no
# sampler thread AND keeps the hot path at zero added work (the timer
# exemplar hook below stays a single module-flag read).
TIMELINE_ENABLED = SystemProperty("geomesa.timeline.enabled", "true")
TIMELINE_INTERVAL = SystemProperty("geomesa.timeline.interval", "1 second")
TIMELINE_WINDOW = SystemProperty("geomesa.timeline.window", "1 hour")
# Durable telemetry spool (utils/history.py): per-tick timeline
# snapshots, SLO violations, breaker transitions, decision tallies, and
# periodic per-fingerprint top-K land write-behind in append-only
# segment files under `<root>/_telemetry/` — the flight recorder that
# survives the process. Segments rotate at `history.bytes` (sealed with
# the store/integrity.py CRC footer; explicit 0 disables size rotation)
# and age out after `history.ttl` (explicit 0 disables the retention
# sweep). `enabled=0` opens no spool, creates no directory, and adds
# zero work anywhere — the sampler hook is one attribute read.
HISTORY_ENABLED = SystemProperty("geomesa.history.enabled", "true")
HISTORY_BYTES = SystemProperty("geomesa.history.bytes", "1MB")
HISTORY_TTL = SystemProperty("geomesa.history.ttl", "24 hours")
# Workload recorder (utils/workload.py): every admitted query/join/
# aggregate/stream appends a REPLAYABLE descriptor — type name, CQL,
# hints, query class, tenant, monotonic arrival offset, in-flight
# concurrency, outcome, plan-fingerprint id, cost receipt — to its own
# CRC-sealed segment kind (`wl-*`) under `<root>/_telemetry/`, so
# scripts/replay_workload.py can re-drive yesterday's traffic against a
# knob change. Default OFF: capture is an opt-in observer, and
# `enabled=0` leaves ONE cached flag read on the hot path (the
# history-spool posture; poisoned-spool test pins it). `literals=0`
# replaces CQL literals with a salted hash before anything touches disk
# (capture without retaining user-supplied values). `bytes`/`ttl`
# mirror the history rotation/retention knobs for the workload segments.
WORKLOAD_ENABLED = SystemProperty("geomesa.workload.enabled", "false")
WORKLOAD_LITERALS = SystemProperty("geomesa.workload.literals", "true")
WORKLOAD_BYTES = SystemProperty("geomesa.workload.bytes", "1MB")
WORKLOAD_TTL = SystemProperty("geomesa.workload.ttl", "24 hours")
# Per-tenant cost metering (utils/tenants.py): the `tenant` query hint
# (web.py maps the X-Geomesa-Tenant header into it; absent = "anon")
# accumulates into a fixed-memory top-K LRU — calls/outcomes/latency/
# rows/receipt sums/per-class splits per tenant — behind
# GET /debug/tenants, the timeline's per-tick tenant deltas, per-tenant
# SLO burn (`<slo>@tenant:<label>` on /healthz), and the fleet rollup.
# `enabled=0` reduces the hot-path hook to a single cached flag read
# (the plans-registry posture). `max` bounds tenants per registry.
TENANTS_ENABLED = SystemProperty("geomesa.tenants.enabled", "true")
TENANTS_MAX = SystemProperty("geomesa.tenants.max", "64")
# Perf-regression sentry (utils/history.py): per-fingerprint EWMA
# latency baselines over the spool's per-tick plan deltas; a sustained
# log2 shift >= `sentry.threshold` covering at least `sentry.min.events`
# query events raises a reason-coded decision("sentry", "regressed"),
# degrades /healthz naming the fingerprint, and clears on recovery.
# Explicit threshold 0 disables the sentry.
SENTRY_THRESHOLD = SystemProperty("geomesa.sentry.threshold", "1.0")
SENTRY_MIN_EVENTS = SystemProperty("geomesa.sentry.min.events", "32")
# SLO engine (utils/slo.py): declarative latency/availability objectives
# per query class (query, join, aggregate, stream first-batch) with
# multi-window burn rates (fast / slow) computed over the timeline ring.
# A class is VIOLATING — /healthz degrades, naming it — when both
# windows burn faster than their thresholds AND the fast window saw at
# least `min.events` (a single failed query on a quiet store must not
# page anyone). `exemplars=1` (with the timeline enabled) makes timer
# reservoirs keep (value, trace_id) exemplars per latency bucket so the
# p99 links straight to a retained trace in /debug/traces.
SLO_ENABLED = SystemProperty("geomesa.slo.enabled", "true")
SLO_EXEMPLARS = SystemProperty("geomesa.slo.exemplars", "true")
SLO_WINDOW_FAST = SystemProperty("geomesa.slo.window.fast", "5 minutes")
SLO_WINDOW_SLOW = SystemProperty("geomesa.slo.window.slow", "1 hour")
SLO_BURN_FAST = SystemProperty("geomesa.slo.burn.fast", "14.4")
SLO_BURN_SLOW = SystemProperty("geomesa.slo.burn.slow", "1.0")
SLO_MIN_EVENTS = SystemProperty("geomesa.slo.min.events", "100")
SLO_AVAILABILITY = SystemProperty("geomesa.slo.availability", "0.999")
SLO_LATENCY_OBJECTIVE = SystemProperty("geomesa.slo.latency.objective", "0.99")
SLO_QUERY_LATENCY_MS = SystemProperty("geomesa.slo.query.latency.ms", "250")
SLO_JOIN_LATENCY_MS = SystemProperty("geomesa.slo.join.latency.ms", "1000")
SLO_AGGREGATE_LATENCY_MS = SystemProperty(
    "geomesa.slo.aggregate.latency.ms", "250"
)
SLO_STREAM_FIRST_LATENCY_MS = SystemProperty(
    "geomesa.slo.stream.first.latency.ms", "250"
)
# Plan-quality telemetry (utils/plans.py): per-fingerprint aggregates —
# normalized plan shape -> calls/outcomes/latency/rows/receipts/
# estimate-vs-actual/decision tallies — behind GET /debug/plans,
# POST /explain, and the timeline's per-tick top-fingerprint deltas.
# `enabled=0` reduces every hot-path hook to a single cached flag read
# (the exemplar-hook posture; poisoned-registry test pins it). `max`
# bounds the top-K LRU of fingerprints per registry (fixed memory).
PLANS_ENABLED = SystemProperty("geomesa.plans.enabled", "true")
PLANS_MAX = SystemProperty("geomesa.plans.max", "256")
# Crash recovery (store/journal.py): corrupt files quarantined by the
# integrity layer are kept for operator inspection, then aged out by the
# store-open scrub once older than this TTL (bounds disk leakage from
# repeated corruption). Raise it (e.g. "3650 days") to keep them longer.
QUARANTINE_TTL = SystemProperty("geomesa.fs.quarantine.ttl", "7 days")
FEATURE_EXPIRY = SystemProperty("geomesa.feature.expiry", None)
# Cold-column spill: when set, record-table columns larger than the
# threshold are written to .npy files under this directory and re-opened
# memory-mapped, so wide schemas at large N stay bounded by the page
# cache instead of the heap (the reference's analog: full features live
# in the backing KV store, not in client memory). Off by default.
SPILL_DIR = SystemProperty("geomesa.spill.dir", None)
SPILL_MIN_BYTES = SystemProperty("geomesa.spill.min.bytes", "4MB")
# Priority classes (utils/admission.py): the `geomesa.query.priority`
# query hint (web.py maps the X-Geomesa-Priority header into it) and the
# per-tenant default map classify every query/join/aggregate/stream as
# critical / interactive / batch / background. `priority.default` names
# the class for unhinted traffic; `admission.critical.reserve` holds
# that many in-flight slots back from NON-critical classes, so a
# background flood can never starve critical traffic even while healthy
# (explicit 0 disables the floor). `tenants.priority` is a per-tenant
# default map, "tenantA=critical,tenantB=background".
PRIORITY_DEFAULT = SystemProperty("geomesa.priority.default", "interactive")
ADMISSION_CRITICAL_RESERVE = SystemProperty(
    "geomesa.admission.critical.reserve", "1"
)
TENANTS_PRIORITY = SystemProperty("geomesa.tenants.priority", None)
# Brownout controller (utils/brownout.py): a deterministic overload
# ladder driven each timeline tick by SLO burn, admission queue depth,
# and breaker states — level 0 normal, 1 sheds background, 2 sheds
# batch and disables hedging + cold speculative builds, 3 fail-fasts
# everything below critical. `enabled=0` is byte-identical to a build
# without the controller. Levels ENTER after `enter.ticks` consecutive
# over-threshold ticks and EXIT after `exit.ticks` clear ones
# (hysteresis — the ladder must never flap on one noisy second).
# `queue.ratio.*` are the admission (queued / max_queue) thresholds for
# levels 1-3; `retry.after.s` is the floor of the burn-derived
# Retry-After that shed responses carry.
BROWNOUT_ENABLED = SystemProperty("geomesa.brownout.enabled", "true")
BROWNOUT_ENTER_TICKS = SystemProperty("geomesa.brownout.enter.ticks", "2")
BROWNOUT_EXIT_TICKS = SystemProperty("geomesa.brownout.exit.ticks", "3")
BROWNOUT_QUEUE_RATIO_1 = SystemProperty("geomesa.brownout.queue.ratio.1", "0.5")
BROWNOUT_QUEUE_RATIO_2 = SystemProperty(
    "geomesa.brownout.queue.ratio.2", "0.75"
)
BROWNOUT_QUEUE_RATIO_3 = SystemProperty(
    "geomesa.brownout.queue.ratio.3", "0.95"
)
BROWNOUT_RETRY_AFTER_S = SystemProperty("geomesa.brownout.retry.after.s", "1")
# Retry budgets (utils/retry.py): a per-boundary token bucket caps
# retries at ~`ratio` of that boundary's traffic (the classic 10% rule)
# so a retry storm can never amplify an overload — exhaustion gives up
# crisply (the original error) and counts retry.<name>.budget_exhausted.
# `min` is a per-SECOND refill floor (the Finagle RetryBudget shape) so
# low-traffic boundaries — and fault-heavy chaos soaks, where injected
# failure rates dwarf any traffic ratio — still recover their ability
# to retry; `cap` bounds the burst a long-idle bucket can save up.
RETRY_BUDGET_ENABLED = SystemProperty("geomesa.retry.budget.enabled", "true")
RETRY_BUDGET_RATIO = SystemProperty("geomesa.retry.budget.ratio", "0.1")
RETRY_BUDGET_MIN = SystemProperty("geomesa.retry.budget.min", "10")
RETRY_BUDGET_CAP = SystemProperty("geomesa.retry.budget.cap", "100")
