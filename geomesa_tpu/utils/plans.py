"""Plan-quality telemetry: query fingerprints + per-fingerprint stats.

PR 10 built the *system-level* half of the measurement loop (timeline,
burn rates, incident reports); this module is the *plan-level* half.
Every query/join/aggregate is fingerprinted by its NORMALIZED plan shape
— feature type, chosen index, union arity, filter shape (node kinds and
property names, literals erased), hint class, and the scan path that
actually answered — and folded into a fixed-memory top-K LRU of
per-fingerprint aggregates (the pg_stat_statements role):

* calls + outcome counts (ok / timeout / shed), hits;
* a latency timer per fingerprint, through ``audit.MetricsRegistry`` —
  so the PR 10 per-tick histograms and trace-linked exemplars come for
  free (``/debug/plans`` links a fingerprint's worst sample straight to
  a retained trace);
* rows scanned / returned and blocks touched (fed per scanned block by
  the store's consume loop);
* cost-receipt sums (recompiles, h2d/d2h bytes, pad ratio);
* **estimate vs actual**: the planner's ``QueryPlan.cost`` and range
  count recorded at plan time vs the candidate rows actually consumed,
  with the misestimate tracked as a log2-ratio histogram — the input the
  ROADMAP's self-driving-analytics knobs (pyramid build/decline, batch
  window, hedge quantile, adaptive join selection) need;
* reason-coded decision tallies (``utils.audit.decision``): which
  adaptive branches fired for queries of THIS shape, and why.

Free when off: ``geomesa.plans.enabled=0`` reduces every hot-path hook
to a single cached module-flag read (``begin``) or one contextvar read
(``note``/``note_scan``/``decision`` tallies) — the fault_point /
trace.span / exemplar-flag posture, asserted by tests/test_plans.py with
a poisoned registry. The flag resolves from the knob once and is cached;
``set_enabled(None)`` re-resolves (tests and config flips).

Surfaces: ``GET /debug/plans`` (top fingerprints, sortable), the
``plans`` section of ``GET /debug/report``, per-tick top-fingerprint
deltas in the flight-recorder timeline, a per-shard rollup through
``ShardWorker.telemetry()``, and ``store.explain_analyze()`` (web.py
``POST /explain``), which joins one live execution's span tree to its
fingerprint record.
"""

from __future__ import annotations

import contextvars
import hashlib
import math
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu.utils.audit import MetricsRegistry, histogram_summary

# -- the flag -----------------------------------------------------------------

_ENABLED: Optional[bool] = None  # None = resolve from the knob on next read


def enabled() -> bool:
    """The hot-path gate: one module-global read once resolved."""
    e = _ENABLED
    if e is None:
        return _resolve()
    return e


def _resolve() -> bool:
    global _ENABLED
    from geomesa_tpu.utils.config import PLANS_ENABLED

    _ENABLED = bool(PLANS_ENABLED.to_bool())
    return _ENABLED


def set_enabled(on: Optional[bool]) -> None:
    """Flip the cached flag (``None`` re-resolves ``geomesa.plans.enabled``
    on the next read — how tests and config flips take effect)."""
    global _ENABLED
    _ENABLED = None if on is None else bool(on)


def plans_knobs() -> Tuple[bool, int]:
    """(enabled, max_fingerprints) from the geomesa.plans.* tier."""
    from geomesa_tpu.utils.config import PLANS_MAX

    cap = PLANS_MAX.to_int()
    return enabled(), 256 if cap is None or cap <= 0 else cap


# -- per-query pending context ------------------------------------------------
#
# Decisions and per-block row counts happen DURING execution, before the
# fingerprint is known (the scan path is part of the key and only final
# at consume time). They collect into a small per-query context object
# installed by ``begin()`` and drained by ``PlanRegistry.observe`` at
# audit time. With the flag down, ``begin`` returns None and every
# ``note*`` is one contextvar read of the None default.

_PENDING_CAP = 64  # bound per-query decision tallies (fixed memory)


class _Pending:
    __slots__ = ("decisions", "rows_in", "rows_out", "blocks")

    def __init__(self):
        self.decisions: List[Tuple[str, str]] = []
        self.rows_in = 0
        self.rows_out = 0
        self.blocks = 0

    def reset(self) -> None:
        self.decisions = []
        self.rows_in = self.rows_out = self.blocks = 0


_PENDING: contextvars.ContextVar[Optional[_Pending]] = contextvars.ContextVar(
    "geomesa_tpu_plan_pending", default=None
)


def begin():
    """Open one query's pending-collection scope (None when disabled —
    the single flag read the off path pays). Pair with ``end``."""
    if not enabled():
        return None
    return _PENDING.set(_Pending())


def end(token) -> None:
    if token is not None:
        _PENDING.reset(token)


def pending() -> Optional["_Pending"]:
    """A detached pending collector for GENERATOR query bodies (None
    when disabled — the same single flag read as ``begin``). A
    contextvar must never stay set across a yield, so streaming paths
    hold the object and re-enter it with ``attach`` around each step,
    the ``deadline.attach`` posture."""
    return _Pending() if enabled() else None


class attach:
    """Re-enter a ``pending()`` scope around one step of a generator
    body; no-op (and allocation-free on __exit__) when ``p`` is None."""

    __slots__ = ("_p", "_tok")

    def __init__(self, p: Optional["_Pending"]):
        self._p = p
        self._tok = None

    def __enter__(self):
        if self._p is not None:
            self._tok = _PENDING.set(self._p)
        return self._p

    def __exit__(self, *exc) -> bool:
        if self._tok is not None:
            _PENDING.reset(self._tok)
            self._tok = None
        return False


def note(point: str, reason: str) -> None:
    """Tally one reason-coded event on the current query's fingerprint
    (cache engagement, adaptive declines — ``utils.audit.decision``
    routes here). No-op outside a ``begin`` scope."""
    p = _PENDING.get()
    if p is not None and len(p.decisions) < _PENDING_CAP:
        p.decisions.append((point, reason))


def note_scan(rows_in: int, rows_out: int) -> None:
    """Fold one scanned block's candidate/result row counts into the
    current query's actuals (the estimate-vs-actual denominator)."""
    p = _PENDING.get()
    if p is not None:
        p.rows_in += int(rows_in)
        p.rows_out += int(rows_out)
        p.blocks += 1


# -- fingerprints -------------------------------------------------------------


def filter_shape(f) -> str:
    """Normalized filter shape: node kinds and property names with every
    literal erased, AND/OR children sorted — two bboxes over the same
    column are ONE shape (the pg_stat_statements normalization rule)."""
    from geomesa_tpu.filter import ast

    if f is None or isinstance(f, ast.Include):
        return "INCLUDE"
    if isinstance(f, ast.Exclude):
        return "EXCLUDE"
    if isinstance(f, (ast.And, ast.Or)):
        kids = sorted(filter_shape(c) for c in f.children())
        return f"{type(f).__name__.upper()}({','.join(kids)})"
    if isinstance(f, ast.Not):
        return f"NOT({filter_shape(f.child)})"
    if isinstance(f, ast.Cmp):
        return f"{f.prop}{f.op}?"
    if isinstance(f, ast.IdFilter):
        return "ID(?)"
    name = type(f).__name__.upper()
    prop = getattr(f, "prop", None)
    return f"{name}({prop})" if prop is not None else f"{name}(?)"


def fingerprint_key(
    kind: str,
    type_name: str,
    plan=None,
    query=None,
    scan_path: str = "",
    shape: Optional[str] = None,
) -> tuple:
    """The normalized plan-shape key: NO literal values, so every bbox
    over the same column/index/path folds into one fingerprint."""
    index = ""
    union_arity = 0
    if plan is not None:
        index = getattr(getattr(plan, "index", None), "name", "") or ""
        union = getattr(plan, "union", None)
        union_arity = len(union) if union else 0
    if shape is None:
        shape = filter_shape(getattr(query, "filter", None))
    hints = getattr(query, "hints", None) or {}
    hint_class = "+".join(sorted(hints))
    return (kind, type_name, index, union_arity, shape, hint_class, scan_path)


def _fid(key: tuple) -> str:
    return hashlib.sha1("|".join(map(str, key)).encode()).hexdigest()[:12]


def fingerprint_id(key: tuple) -> str:
    """The stable short id of one fingerprint key — what /debug/plans
    rows and explain_analyze join on."""
    return _fid(key)


def _mis_bucket(actual: float, estimate: float) -> int:
    """Signed log2 misestimate bucket: 0 = spot-on, +k = the plan
    under-estimated by ~2^k, -k = over-estimated. +1 smoothing keeps
    empty results and zero-cost plans finite."""
    return int(round(math.log2((actual + 1.0) / (max(estimate, 0.0) + 1.0))))


class PlanEntry:
    """One fingerprint's aggregates (mutated under the registry lock)."""

    __slots__ = (
        "fid", "kind", "type_name", "index", "union_arity", "shape",
        "hint_class", "scan_path", "calls", "outcomes", "hits",
        "rows_scanned", "rows_returned", "blocks", "total_s", "last_ms",
        "est_cost_sum", "est_ranges_sum", "est_calls", "mis_hist",
        "recompiles", "h2d_bytes", "d2h_bytes", "pad_ratio_sum",
        "pad_calls", "decisions",
    )

    def __init__(self, fid: str, key: tuple):
        (self.kind, self.type_name, self.index, self.union_arity,
         self.shape, self.hint_class, self.scan_path) = key
        self.fid = fid
        self.calls = 0
        self.outcomes: Dict[str, int] = {}
        self.hits = 0
        self.rows_scanned = 0
        self.rows_returned = 0
        self.blocks = 0
        self.total_s = 0.0
        self.last_ms = 0.0
        self.est_cost_sum = 0.0
        self.est_ranges_sum = 0
        self.est_calls = 0
        self.mis_hist: Dict[int, int] = {}
        self.recompiles = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.pad_ratio_sum = 0.0
        self.pad_calls = 0
        self.decisions: Dict[str, int] = {}

    def mean_log2_mis(self) -> Optional[float]:
        n = sum(self.mis_hist.values())
        if not n:
            return None
        return sum(b * c for b, c in self.mis_hist.items()) / n

    def row(self) -> Dict[str, Any]:
        est_n = max(self.est_calls, 1)
        mis = self.mean_log2_mis()
        return {
            "fingerprint": self.fid,
            "kind": self.kind,
            "type": self.type_name,
            "index": self.index,
            "union_arity": self.union_arity,
            "shape": self.shape,
            "hints": self.hint_class,
            "scan_path": self.scan_path,
            "calls": self.calls,
            "outcomes": dict(self.outcomes),
            "hits": self.hits,
            "rows_scanned": self.rows_scanned,
            "rows_returned": self.rows_returned,
            "blocks": self.blocks,
            "total_ms": round(self.total_s * 1000.0, 3),
            "last_ms": round(self.last_ms, 3),
            "estimate": {
                "cost_mean": round(self.est_cost_sum / est_n, 2),
                "ranges_mean": round(self.est_ranges_sum / est_n, 2),
                # the weighting count: merge_rows recomputes exact
                # weighted means across shards from mean * calls
                "calls": self.est_calls,
            },
            "actual": {
                "rows_mean": round(self.rows_scanned / max(self.calls, 1), 2),
            },
            "misestimate": {
                "hist": {str(b): c for b, c in sorted(self.mis_hist.items())},
                "mean_log2": None if mis is None else round(mis, 3),
            },
            "receipt": {
                "recompiles": self.recompiles,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "pad_ratio_mean": round(
                    self.pad_ratio_sum / max(self.pad_calls, 1), 4
                ),
                "pad_calls": self.pad_calls,
            },
            "decisions": dict(self.decisions),
        }


_SORTS = {
    "time": lambda r: r["total_ms"],
    "calls": lambda r: r["calls"],
    "hits": lambda r: r["hits"],
    "misestimate": lambda r: abs(r["misestimate"]["mean_log2"] or 0.0),
}
# the public sort-key whitelist (web.py validates ?sort= against THIS,
# so a new key here is served route-side without a shadow copy to drift)
SORTS = tuple(_SORTS)


class PlanRegistry:
    """Fixed-memory top-K LRU of per-fingerprint aggregates.

    One registry per store (``TpuDataStore._plans_obj``; a ShardWorker
    shares ONE across its partition sub-stores so the per-shard rollup
    is one read). Latency rides ``self.metrics`` timers named
    ``plan.<fid>`` — the shared MetricsRegistry reservoir/exemplar
    machinery, dropped with the entry on LRU eviction so memory stays
    bounded by the cap alone."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = plans_knobs()[1] if cap is None else int(cap)
        self.metrics = MetricsRegistry()
        self._entries: "OrderedDict[tuple, PlanEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.evicted = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def observe(
        self,
        kind: str,
        type_name: str,
        *,
        plan=None,
        query=None,
        scan_path: str = "",
        shape: Optional[str] = None,
        outcome: str = "ok",
        hits: int = 0,
        duration_s: float = 0.0,
        receipt: Optional[Dict[str, Any]] = None,
        est_cost: Optional[float] = None,
        est_ranges: Optional[int] = None,
    ) -> str:
        """Fold one finished query into its fingerprint (LRU-bumped;
        evicts the coldest entry past the cap). Drains the pending
        context (decisions + per-block row actuals) and resets it, so a
        nested consumer (an aggregate's exact-fallback inner query) can
        never double-report. Returns the fingerprint id."""
        key = fingerprint_key(
            kind, type_name, plan=plan, query=query, scan_path=scan_path,
            shape=shape,
        )
        pend = _PENDING.get()
        dropped = None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = PlanEntry(_fid(key), key)
                self._entries[key] = e
                if len(self._entries) > self.cap:
                    _k, dropped = self._entries.popitem(last=False)
                    self.evicted += 1
            else:
                self._entries.move_to_end(key)
            e.calls += 1
            e.outcomes[outcome] = e.outcomes.get(outcome, 0) + 1
            e.hits += int(hits)
            e.total_s += float(duration_s)
            e.last_ms = float(duration_s) * 1000.0
            if receipt:
                e.recompiles += int(receipt.get("recompiles", 0))
                e.h2d_bytes += int(receipt.get("h2d_bytes", 0))
                e.d2h_bytes += int(receipt.get("d2h_bytes", 0))
                pr = float(receipt.get("pad_ratio", 0.0))
                if pr > 0.0:
                    e.pad_ratio_sum += pr
                    e.pad_calls += 1
            if pend is not None:
                e.rows_scanned += pend.rows_in
                e.rows_returned += pend.rows_out
                e.blocks += pend.blocks
                for point, reason in pend.decisions:
                    k = f"{point}.{reason}"
                    e.decisions[k] = e.decisions.get(k, 0) + 1
            if est_cost is not None:
                e.est_cost_sum += float(est_cost)
                e.est_ranges_sum += int(est_ranges or 0)
                e.est_calls += 1
                # the misestimate bucket needs REAL actuals: a coalesced
                # follower's scan ran in the leader's context, so its own
                # pending saw zero blocks — bucketing 0 against a true
                # cost would read as a huge over-estimate and poison the
                # signal the adaptive knobs consume. No blocks observed
                # -> no verdict (hits stand in only when no pending
                # scope existed at all).
                if pend is None:
                    b = _mis_bucket(int(hits), float(est_cost))
                    e.mis_hist[b] = e.mis_hist.get(b, 0) + 1
                elif pend.blocks > 0:
                    b = _mis_bucket(pend.rows_in, float(est_cost))
                    e.mis_hist[b] = e.mis_hist.get(b, 0) + 1
            fid = e.fid
        if pend is not None:
            pend.reset()
        if dropped is not None:
            self.metrics.drop_timer(f"plan.{dropped.fid}")
        # the timer update sits OUTSIDE the registry lock: reservoir,
        # cumulative totals, and (flag-up) exemplars ride the shared
        # MetricsRegistry machinery — PR 10 histograms come free
        self.metrics.update_timer(f"plan.{fid}", float(duration_s))
        return fid

    # -- reads ---------------------------------------------------------------

    def rows(self, sort: str = "time", n: int = 20) -> List[Dict[str, Any]]:
        """Top ``n`` fingerprint rows by ``sort`` (time | calls | hits |
        misestimate), latency summaries and trace-linked exemplars
        attached. Entries are copied under the lock; timer reads happen
        after (the registry-lock-then-metrics-lock order is the only one
        used anywhere, so no inversion)."""
        if sort not in _SORTS:
            raise ValueError(
                f"unknown sort {sort!r} (one of {sorted(_SORTS)})"
            )
        with self._lock:
            rows = [e.row() for e in self._entries.values()]
        rows.sort(key=_SORTS[sort], reverse=True)
        rows = rows[: max(0, int(n))]
        _c, _g, timers, totals = self.metrics.snapshot()
        for r in rows:
            vals = timers.get(f"plan.{r['fingerprint']}")
            if vals:
                r["latency"] = histogram_summary(
                    vals,
                    total_count=totals.get(
                        f"plan.{r['fingerprint']}", (None,)
                    )[0],
                )
            ex = self.metrics.exemplars(f"plan.{r['fingerprint']}")
            if ex and ex.get("buckets"):
                b = max(ex["buckets"])
                s, tid, wall = ex["buckets"][b]
                if tid:
                    r["worst_exemplar"] = {
                        "ms": round(s * 1000.0, 3),
                        "trace_id": tid,
                        "date_ms": int(wall),
                    }
        return rows

    def top(self, n: int = 5) -> List[Dict[str, Any]]:
        """Compact per-shard/timeline summary: the ``n`` hottest
        fingerprints by total time."""
        with self._lock:
            es = sorted(
                self._entries.values(), key=lambda e: e.total_s, reverse=True
            )[: max(0, int(n))]
            return [
                {
                    "fingerprint": e.fid,
                    "type": e.type_name,
                    "index": e.index,
                    "scan_path": e.scan_path,
                    "calls": e.calls,
                    "total_ms": round(e.total_s * 1000.0, 3),
                }
                for e in es
            ]

    def totals(self) -> Dict[str, Tuple[int, float, str]]:
        """{fid: (calls, total_s, type)} — the timeline sampler diffs
        consecutive reads into per-tick top-fingerprint deltas."""
        with self._lock:
            return {
                e.fid: (e.calls, e.total_s, e.type_name)
                for e in self._entries.values()
            }

    def payload(self, sort: str = "time", n: int = 20) -> Dict[str, Any]:
        """The GET /debug/plans body (single-store edition; the sharded
        coordinator wraps this with its per-shard rollup)."""
        return {
            "enabled": enabled(),
            "sort": sort,
            "count": len(self),
            "evicted": self.evicted,
            "fingerprints": self.rows(sort=sort, n=n),
        }


def timeline_deltas(
    registry: Optional[PlanRegistry],
    prev: Dict[str, Tuple[int, float, str]],
    n: int = 5,
) -> Tuple[Dict[str, Tuple[int, float, str]], List[Dict[str, Any]]]:
    """One timeline tick's top-fingerprint deltas: (new_prev, rows) —
    the per-tick "which plan shapes were hot THIS second" block. Pure
    reads; an absent/empty registry returns no rows."""
    if registry is None:
        return prev, []
    now = registry.totals()
    rows = []
    for fid, (calls, total_s, tname) in now.items():
        pc, ps, _t = prev.get(fid, (0, 0.0, tname))
        dc = calls - pc
        if dc <= 0:
            continue
        rows.append({
            "fingerprint": fid,
            "type": tname,
            "calls": dc,
            "ms": round((total_s - ps) * 1000.0, 3),
        })
    rows.sort(key=lambda r: r["ms"], reverse=True)
    return now, rows[: max(0, int(n))]


def history_rows(
    registry: Optional[PlanRegistry], n: int = 10
) -> List[Dict[str, Any]]:
    """The durable-spool edition of the top-K (utils/history.py
    ``plans`` records): per-fingerprint cumulative calls/latency,
    scan path, and the estimate-vs-actual misestimate histogram — the
    recorded statistics the ROADMAP's auto-tuning arc needs to outlive
    the process. A slice of ``rows()``, not the whole row: receipts and
    exemplar pointers stay in memory, the spool keeps what a future
    planner correction would actually consume."""
    if registry is None:
        return []
    out = []
    for r in registry.rows(sort="time", n=n):
        out.append({
            "fingerprint": r["fingerprint"],
            "type": r.get("type"),
            "scan_path": r.get("scan_path"),
            "calls": r.get("calls"),
            "total_ms": r.get("total_ms"),
            "rows_scanned": r.get("rows_scanned"),
            "estimate": r.get("estimate"),
            "misestimate": r.get("misestimate"),
        })
    return out


def merge_rows(row_lists: List[List[Dict[str, Any]]]) -> List[Dict[str, Any]]:
    """Merge fingerprint rows from several registries (the sharded
    rollup): numeric aggregates sum by fingerprint id and every mean
    (estimate cost/ranges, actual rows, pad ratio) is recomputed as an
    EXACT weighted mean from ``mean * count`` — a merged row must never
    report one shard's mean beside a fleet-wide call count. Latency
    summaries and exemplars are per-source and dropped from merged rows
    (percentile reservoirs do not merge — the per-shard blocks keep
    them)."""
    out: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    for rows in row_lists:
        for r in rows:
            fid = r["fingerprint"]
            m = out.get(fid)
            if m is None:
                m = {k: v for k, v in r.items()
                     if k not in ("latency", "worst_exemplar")}
                m["outcomes"] = dict(r.get("outcomes", {}))
                m["decisions"] = dict(r.get("decisions", {}))
                m["misestimate"] = {
                    "hist": dict(r["misestimate"]["hist"]),
                    "mean_log2": r["misestimate"]["mean_log2"],
                }
                m["estimate"] = dict(r["estimate"])
                m["actual"] = dict(r["actual"])
                m["receipt"] = dict(r["receipt"])
                out[fid] = m
                continue
            for k in ("calls", "hits", "rows_scanned", "rows_returned",
                      "blocks"):
                m[k] += r.get(k, 0)
            m["total_ms"] = round(m["total_ms"] + r["total_ms"], 3)
            for k, v in r.get("outcomes", {}).items():
                m["outcomes"][k] = m["outcomes"].get(k, 0) + v
            for k, v in r.get("decisions", {}).items():
                m["decisions"][k] = m["decisions"].get(k, 0) + v
            for k, v in r["misestimate"]["hist"].items():
                m["misestimate"]["hist"][k] = (
                    m["misestimate"]["hist"].get(k, 0) + v
                )
            # weighted-mean folds: mean * count sums exactly
            me, re_ = m["estimate"], r["estimate"]
            for k in ("cost_mean", "ranges_mean"):
                me[k] = me[k] * me["calls"] + re_[k] * re_["calls"]
            me["calls"] += re_["calls"]
            for k in ("cost_mean", "ranges_mean"):
                me[k] = round(me[k] / max(me["calls"], 1), 2)
            mr, rr = m["receipt"], r["receipt"]
            pad_sum = (
                mr["pad_ratio_mean"] * mr.get("pad_calls", 0)
                + rr["pad_ratio_mean"] * rr.get("pad_calls", 0)
            )
            mr["pad_calls"] = mr.get("pad_calls", 0) + rr.get("pad_calls", 0)
            mr["pad_ratio_mean"] = round(
                pad_sum / max(mr["pad_calls"], 1), 4
            )
            for k in ("recompiles", "h2d_bytes", "d2h_bytes"):
                mr[k] += rr.get(k, 0)
    merged = list(out.values())
    for m in merged:
        hist = m["misestimate"]["hist"]
        total = sum(hist.values())
        m["misestimate"]["mean_log2"] = (
            round(sum(int(b) * c for b, c in hist.items()) / total, 3)
            if total else None
        )
        m["actual"]["rows_mean"] = round(
            m["rows_scanned"] / max(m["calls"], 1), 2
        )
    merged.sort(key=lambda r: r["total_ms"], reverse=True)
    return merged
