"""Workload capture: the replayable record of served traffic.

Everything the observability tier records so far is *about* queries —
receipts (PR 3), plan fingerprints (PR 11), timeline ticks (PR 10),
durable history (PR 17). None of it records the queries THEMSELVES, so
no knob change (batch window, hedge quantile, pyramid build threshold,
shard deadline fraction) can ever be evaluated against the traffic that
actually hit the store. This module is that missing instrument: with
``geomesa.workload.enabled=1`` every admitted query / join / aggregate
/ stream appends a **replayable descriptor** — type name, CQL, hints,
query class, tenant label, monotonic arrival offset, in-flight
concurrency at admission, outcome, plan-fingerprint id, cost receipt —
to its own segment kind (``wl-*``) under ``<root>/_telemetry/``, and
``scripts/replay_workload.py`` re-drives the captured stream against
any store at recorded (or accelerated) pacing.

The capture is a **pure observer**, enforced three ways:

* **off is free** — the default. ``geomesa.workload.enabled=0`` leaves
  ONE cached module-flag read on the hot path (the plans-registry
  posture; the poisoned-spool test pins it).
* **on never perturbs** — ``record()`` only builds a dict and queues it
  in a bounded list: no I/O, no lock shared with execution, and any
  internal failure is swallowed (counted ``workload.record.errors``).
  Overflow past the queue bound drops the NEW record (counted
  ``workload.dropped``) — the recorder may lose traffic, never delay
  it.
* **flush is off the query path** — the queue drains on the timeline
  sampler's tick thread (or an explicit ``flush()``), span-wrapped
  (``workload.append``), fault-injectable, and budget-bounded exactly
  like the history spool; a dead telemetry disk re-queues bounded and
  degrades to counted drops.

Privacy: ``geomesa.workload.literals=0`` replaces every quoted CQL
string literal with a salted hash (``'h:<12hex>'``) before anything is
queued — capture keeps the workload *shape* without retaining
user-supplied values. Hashed captures still replay structurally (the
hashes parse as strings), but result-set comparison is meaningless for
them; the replay harness marks such records and skips result hashing.

Segments rotate at ``geomesa.workload.bytes`` (CRC-sealed via
store/integrity.py) and age out after ``geomesa.workload.ttl``; the
reader is ``utils/history.read_records`` pointed at the ``wl-`` prefix,
so sealed-segment verification, corrupt-segment quarantine, and
torn-line skipping are the one shared discipline.
"""

from __future__ import annotations

import atexit
import contextvars
import hashlib
import json
import logging
import os
import re
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu.utils import deadline
from geomesa_tpu.utils.audit import robustness_metrics

_log = logging.getLogger("geomesa_tpu.workload")

# the workload spool's own segment kind, beside history's "seg-" under
# the same <root>/_telemetry/ directory (each reader filters by prefix,
# so the two spools never see each other's segments)
SEGMENT_PREFIX = "wl-"
# write-behind queue bound: a wedged disk (or no sampler draining us)
# degrades the RECORDING — drops, counted — never a query
PENDING_CAP = 512
# per-flush budget: the tick thread pays at most this for durability
FLUSH_BUDGET_S = 0.5

# -- the cached flag (the plans.enabled() posture) ----------------------------

_ENABLED: Optional[bool] = None
_LITERALS: Optional[bool] = None
_FLAG_LOCK = threading.Lock()


def enabled() -> bool:
    """ONE cached read on the hot path — the entire cost of
    ``geomesa.workload.enabled=0`` (default)."""
    e = _ENABLED
    if e is None:
        e = _resolve()
    return e


def raw_literals() -> bool:
    """Whether captured CQL keeps its raw literals (default) or hashes
    them (``geomesa.workload.literals=0``). Cached beside the flag."""
    if _ENABLED is None:
        _resolve()
    return bool(_LITERALS)


def _resolve() -> bool:
    global _ENABLED, _LITERALS
    from geomesa_tpu.utils.config import (
        WORKLOAD_ENABLED,
        WORKLOAD_LITERALS,
    )

    with _FLAG_LOCK:
        _LITERALS = bool(WORKLOAD_LITERALS.to_bool())
        _ENABLED = bool(WORKLOAD_ENABLED.to_bool())
        return _ENABLED


def set_enabled(value: Optional[bool]) -> None:
    """Test hook (the plans.set_enabled contract): ``None`` re-resolves
    from config on the next read, a bool forces."""
    global _ENABLED, _LITERALS
    with _FLAG_LOCK:
        if value is None:
            _ENABLED = None
            _LITERALS = None
        else:
            # forcing the flag must still resolve the literals knob —
            # a forced-on capture with _LITERALS left None would scrub
            # every literal (None is falsy) against the raw default
            from geomesa_tpu.utils.config import WORKLOAD_LITERALS

            _ENABLED = bool(value)
            _LITERALS = bool(WORKLOAD_LITERALS.to_bool())


def workload_knobs() -> Tuple[bool, int, float]:
    """(enabled, segment_bytes, ttl_s) from the geomesa.workload.* tier;
    explicit zeros honored (the history_knobs contract)."""
    from geomesa_tpu.utils.config import (
        WORKLOAD_BYTES,
        WORKLOAD_ENABLED,
        WORKLOAD_TTL,
    )

    en = bool(WORKLOAD_ENABLED.to_bool())
    b = WORKLOAD_BYTES.to_bytes()
    seg_bytes = (1 << 20) if b is None else int(b)
    t = WORKLOAD_TTL.to_duration_s()
    ttl_s = 24 * 3600.0 if t is None else float(t)
    return en, seg_bytes, ttl_s


# -- op nesting ---------------------------------------------------------------

# context-local operation depth (the admission reentrancy idiom): a
# join's inner build/probe queries and an aggregate's exact-fallback
# query audit themselves too, so their captures would double when the
# replay harness re-drives the OUTER op. Depth > 1 at record time marks
# the descriptor ``nested`` — metered and counted like everything else,
# but never directly re-driven.
_OP_DEPTH: contextvars.ContextVar[int] = contextvars.ContextVar(
    "workload_op_depth", default=0
)


def op_begin() -> "contextvars.Token[int]":
    """Mark entry into a public store operation (query / aggregate /
    join / stream). Pair with ``op_end(token)`` in a finally."""
    return _OP_DEPTH.set(_OP_DEPTH.get() + 1)


def op_end(token: "contextvars.Token[int]") -> None:
    _OP_DEPTH.reset(token)


def nested() -> bool:
    """True when the current context is inside an OUTER store op."""
    return _OP_DEPTH.get() > 1


# -- literal scrubbing --------------------------------------------------------

# quoted CQL string literals, '' being the escaped quote — the only
# place user-supplied VALUES appear in the normalized to_cql form
# (numbers in geometric/temporal predicates are shapes, kept: the
# workload's spatial structure IS the signal the knob lab needs)
_LITERAL_RE = re.compile(r"'(?:[^']|'')*'")
# per-process salt: equal literals stay equal WITHIN a capture (the
# workload shape survives), but the hash is not a dictionary lookup
_SALT = os.urandom(8).hex()


def scrub_cql(cql: str) -> str:
    """Replace every quoted string literal with ``'h:<12hex>'`` of its
    salted hash — capture without retaining user-supplied values."""

    def _sub(m: "re.Match[str]") -> str:
        h = hashlib.sha1(
            (_SALT + m.group(0)).encode("utf-8")
        ).hexdigest()[:12]
        return f"'h:{h}'"

    return _LITERAL_RE.sub(_sub, cql)


# -- the spool ----------------------------------------------------------------


class WorkloadSpool:
    """One process's workload-capture spool under ``<root>/_telemetry``
    (``wl-*`` segments). The HistorySpool write-behind discipline minus
    the black box / live markers / sentry — capture is a log, not a
    crash recorder. ``append()`` only queues (bounded, never blocks,
    never raises); ``flush()`` runs on the sampler-tick thread under
    the ``workload.append`` span/fault-point/deadline discipline."""

    def __init__(self, root: str, owner: str = ""):
        from geomesa_tpu.utils.history import TELEMETRY_DIR

        self.root = os.path.abspath(root)
        self.dir = os.path.join(self.root, TELEMETRY_DIR)
        self.owner = owner or f"pid{os.getpid()}"
        _en, self.seg_bytes, self.ttl_s = workload_knobs()
        self._lock = threading.Lock()
        self._pending: List[Dict[str, Any]] = []
        self._active: Optional[str] = None
        self._active_size = 0
        self._seq = 0
        self._closed = False
        # the capture epoch: every record's `off` is monotonic seconds
        # since this instant — recorded pacing, immune to wall clock
        # jumps, exactly what open-loop replay re-sleeps
        self.epoch = time.monotonic()
        self.epoch_t = time.time()
        os.makedirs(self.dir, exist_ok=True)
        atexit.register(self._atexit)

    def append(self, record: Dict[str, Any]) -> None:
        """Queue one descriptor (bounded; DROPS past the cap, counted
        ``workload.dropped``). Safe from any thread; never blocks on
        I/O, never raises — the only call a query thread ever makes."""
        with self._lock:
            if self._closed or len(self._pending) >= PENDING_CAP:
                if not self._closed:
                    robustness_metrics().inc("workload.dropped")
                return
            self._pending.append(record)

    def flush(self) -> int:
        """Drain the queue to the active segment: span-wrapped,
        fault-injectable, budget-bounded — a wedged disk costs the tick
        at most ``FLUSH_BUDGET_S`` and the batch re-queues (bounded)
        for the next tick. Returns records written."""
        from geomesa_tpu.utils import faults, trace

        with self._lock:
            if self._closed or not self._pending:
                return 0
            batch, self._pending = self._pending, []
        try:
            with trace.span("workload.append") as sp:
                with deadline.budget(FLUSH_BUDGET_S):
                    deadline.check("workload.append")
                    faults.fault_point("workload.append")
                    n = self._write(batch)
                sp.set_attr("records", n)
            return n
        except Exception as e:  # noqa: BLE001 - capture degrades, never raises
            robustness_metrics().inc("workload.append.errors")
            _log.debug("workload flush failed, re-queueing: %s", e)
            with self._lock:
                merged = batch + self._pending
                dropped = len(merged) - PENDING_CAP
                if dropped > 0:
                    # oldest-first drop: the tail is closest to "now"
                    merged = merged[dropped:]
                    robustness_metrics().inc("workload.dropped", dropped)
                self._pending = merged
            return 0

    def _write(self, batch: List[Dict[str, Any]]) -> int:
        if self._active is None:
            # the sequence suffix keeps two rotations inside the same
            # millisecond from reusing a SEALED segment's name (an
            # append past its CRC footer would corrupt it)
            self._seq += 1
            self._active = os.path.join(
                self.dir,
                f"{SEGMENT_PREFIX}{int(time.time() * 1000)}"
                f"-{os.getpid()}-{self._seq}.jsonl",
            )
            self._active_size = 0
        data = b"".join(
            json.dumps(rec, default=str).encode("utf-8") + b"\n"
            for rec in batch
        )
        with open(self._active, "ab") as fh:
            fh.write(data)
        self._active_size += len(data)
        if self.seg_bytes and self._active_size >= self.seg_bytes:
            self._rotate()
        return len(batch)

    def _rotate(self) -> None:
        """Seal (CRC footer — the reader verifies) and sweep."""
        from geomesa_tpu.store import integrity

        sealed, self._active = self._active, None
        self._active_size = 0
        try:
            integrity.append_crc_footer(sealed)
            integrity.fsync_dir(self.dir)
        except OSError:
            robustness_metrics().inc("workload.append.errors")
        robustness_metrics().inc("workload.segments.sealed")
        self._sweep()

    def _sweep(self) -> None:
        if not self.ttl_s:
            return
        cutoff = time.time() - self.ttl_s
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if not (name.startswith(SEGMENT_PREFIX)
                    and name.endswith(".jsonl")):
                continue
            path = os.path.join(self.dir, name)
            if path == self._active:
                continue
            try:
                if os.stat(path).st_mtime < cutoff:
                    os.remove(path)
                    robustness_metrics().inc("workload.segments.expired")
            except OSError:
                continue

    def close(self) -> None:
        """Drain and seal; idempotent (also the atexit path)."""
        from geomesa_tpu.store import integrity

        with self._lock:
            if self._closed:
                return
            self._closed = True
            batch, self._pending = self._pending, []
            active = self._active
            self._active = None
        try:
            if batch:
                self._active = active  # resume (or open) for the drain
                self._write(batch)
                active, self._active = self._active, None
        except OSError:
            robustness_metrics().inc("workload.append.errors")
        try:
            if active and os.path.exists(active):
                integrity.append_crc_footer(active)
            integrity.fsync_dir(self.dir)
        except OSError:
            pass

    def _atexit(self) -> None:
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def segments(self) -> List[str]:
        try:
            return sorted(
                n for n in os.listdir(self.dir)
                if n.startswith(SEGMENT_PREFIX) and n.endswith(".jsonl")
            )
        except OSError:
            return []

    def info(self) -> Dict[str, Any]:
        counters, _g, _t, _tt = robustness_metrics().snapshot()
        return {
            "dir": self.dir,
            "owner": self.owner,
            "segments": len(self.segments()),
            "pending": len(self._pending),
            "dropped": counters.get("workload.dropped", 0),
        }


# -- per-store spools (the history.spool_for arrangement) ---------------------

_SPOOLS: "weakref.WeakKeyDictionary[Any, WorkloadSpool]" = (
    weakref.WeakKeyDictionary()
)
_SPOOLS_LOCK = threading.Lock()


def open_spool(root: str, owner: str = "") -> Optional[WorkloadSpool]:
    """A spool at an explicit root, or None when capture is off / the
    directory cannot be created — disabled capture must cost nothing
    and break nothing."""
    if not enabled() or not root:
        return None
    try:
        return WorkloadSpool(root, owner=owner)
    except OSError:
        _log.warning("workload spool unavailable at %s", root,
                     exc_info=True)
        return None


def spool_for(store: Any, create: bool = True) -> Optional[WorkloadSpool]:
    """The store's capture spool, keyed weakly; only stores with a
    durable ``root`` can capture — everything else answers None."""
    root = getattr(store, "root", None)
    if not isinstance(root, str) or not root:
        return None
    with _SPOOLS_LOCK:
        got = _SPOOLS.get(store)
        if got is not None or not create:
            return got
        sp = open_spool(root, owner=type(store).__name__)
        if sp is not None:
            _SPOOLS[store] = sp
        return sp


def flush_for(store: Any) -> None:
    """The tick-thread drain hook (utils/timeline.py): flush an
    EXISTING spool only — a sampler tick must never be what opens one
    (the engine_for create=False posture)."""
    sp = spool_for(store, create=False)
    if sp is not None:
        sp.flush()


# -- the hot-path hook --------------------------------------------------------


def record(
    store: Any,
    cls: str,
    type_name: str,
    *,
    query: Any = None,
    cql: Optional[str] = None,
    tenant: str = "anon",
    inflight: int = 0,
    outcome: str = "ok",
    fingerprint: str = "",
    receipt: Optional[Dict[str, Any]] = None,
    duration_s: float = 0.0,
    rows: int = 0,
    extra: Optional[Dict[str, Any]] = None,
) -> None:
    """Capture one served request. Called from the datastore observe
    seams INSIDE the admission slot (so ``inflight`` reflects the
    concurrency the query actually ran under). Pure-observer contract:
    when capture is off this is ONE cached flag read; when on, any
    internal failure is swallowed (counted ``workload.record.errors``)
    — the recorder may lose a record, never perturb a query."""
    if not enabled():
        return
    try:
        sp = spool_for(store)
        if sp is None:
            return
        text = cql
        hints: Dict[str, Any] = {}
        max_features = None
        if query is not None:
            if text is None:
                from geomesa_tpu.filter.parser import to_cql

                text = to_cql(query.filter)
            hints = {
                k: v for k, v in (query.hints or {}).items()
                if k != "tenant"  # travels in its own field
            }
            max_features = query.max_features
        literals = "raw"
        if text is not None and not raw_literals():
            text = scrub_cql(text)
            literals = "hashed"
        rec: Dict[str, Any] = {
            "kind": "workload",
            "t": time.time(),
            "off": round(time.monotonic() - sp.epoch, 6),
            "cls": cls,
            "type": type_name,
            "cql": text,
            "tenant": tenant,
            "inflight": int(inflight),
            "outcome": outcome,
            "fingerprint": fingerprint,
            "ms": round(float(duration_s) * 1000.0, 3),
            "rows": int(rows),
            "literals": literals,
        }
        if hints:
            rec["hints"] = hints
        if max_features is not None:
            rec["max"] = int(max_features)
        if receipt:
            rec["receipt"] = dict(receipt)
        if extra:
            rec.update(extra)
        if nested():
            # an inner op of the outer record above it — replay drives
            # the outer one; re-driving this too would double it
            rec["nested"] = 1
        sp.append(rec)
    except Exception:  # noqa: BLE001 - a capture bug must never fail a query
        robustness_metrics().inc("workload.record.errors")
        _log.debug("workload record failed", exc_info=True)


# -- the reader ---------------------------------------------------------------


def read_workload(
    root: str,
    s: Optional[float] = None,
    until: Optional[float] = None,
    limit: Optional[int] = None,
) -> Tuple[List[Dict[str, Any]], bool]:
    """Captured descriptors under ``<root>/_telemetry`` (``wl-*``),
    oldest first, via the shared verified reader — sealed-segment CRC
    checks, corrupt-segment quarantine (``workload.segments.corrupt``),
    torn-line skips (``workload.torn``). Disk-only: a SIGKILLed
    process's capture reads the same as a live one."""
    from geomesa_tpu.utils import history as _history

    return _history.read_records(
        root, s=s, until=until, limit=limit,
        prefix=SEGMENT_PREFIX, counter_ns="workload",
    )
