"""Cross-process mutex for the axon TPU tunnel.

Concurrent axon claims deadlock each other (observed round 2), so every
process that may initialize the TPU backend — bench.py, bench_suite.py,
scripts/tpu_watch.py — serializes through one advisory flock. Probes use
``try_acquire`` (non-blocking): if another process holds the tunnel, treat
the TPU as busy rather than queueing up behind a long hardware batch.
"""

from __future__ import annotations

import fcntl
import os
import time
from contextlib import contextmanager
from typing import Iterator, Optional

LOCK_PATH = os.environ.get("GEOMESA_AXON_LOCK", "/tmp/geomesa_axon.lock")


class AxonLock:
    def __init__(self, path: str = LOCK_PATH):
        self.path = path
        self._fh = None

    def try_acquire(self, timeout_s: float = 0.0, poll_s: float = 2.0) -> bool:
        """Acquire without blocking (optionally retrying until timeout_s).
        Returns False if another process holds the tunnel."""
        if self._fh is not None:
            return True
        deadline = time.monotonic() + timeout_s
        fh = open(self.path, "a+")
        while True:
            try:
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._fh = fh
                return True
            except OSError:
                if time.monotonic() >= deadline:
                    fh.close()
                    return False
                time.sleep(poll_s)

    def release(self) -> None:
        if self._fh is not None:
            try:
                fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
            finally:
                self._fh.close()
                self._fh = None


@contextmanager
def axon_claim(timeout_s: float = 0.0) -> Iterator[Optional[AxonLock]]:
    """Context manager yielding the held lock, or None when busy."""
    lock = AxonLock()
    got = lock.try_acquire(timeout_s)
    try:
        yield lock if got else None
    finally:
        lock.release()
