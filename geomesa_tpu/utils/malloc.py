"""Glibc arena retention for allocation-churn-heavy paths (bulk ingest).

This environment backs anonymous memory lazily: faulting fresh pages runs
at ~70-140 MB/s (measured; a normal box does GB/s). Glibc's default
behavior — mmap for allocations >128 KB, munmap on free, trim the heap
back to the OS — makes every transient batch buffer re-fault its pages on
the NEXT batch, which collapsed converter ingest from ~600k to ~277k
rec/s as RSS grew (NOTES_ROUND3.md "env-level alloc slowdown").

Measured fix: keep freed memory in the process (M_TRIM_THRESHOLD=max,
M_MMAP_THRESHOLD=max) so batch N+1 reuses batch N's already-faulted
pages. Repeated 512 MB alloc+fault+free cycles: ~550 ms -> ~8 ms.

Deliberately opt-in per path (bulk ingest, benchmarks): a library must
not silently pin every caller's high-water RSS. GEOMESA_MALLOC_RETAIN=0
disables. The reference's JVM runtime makes the same trade by holding its
heap; this is the CPython/glibc equivalent
(tools/ingest/AbstractIngest.scala role: sustained batch throughput).
"""

import ctypes
import os

_done = None

# glibc mallopt parameter numbers (malloc.h)
_M_TRIM_THRESHOLD = -1
_M_MMAP_THRESHOLD = -3


def retain_freed_memory() -> bool:
    """Keep freed memory in-process via glibc heap-trim/mmap thresholds
    (NOT arena management — M_ARENA_MAX is untouched). Idempotent: the
    mallopt pair is applied at most once per process and cannot be undone,
    so GEOMESA_MALLOC_RETAIN=0 only has effect if set before the first
    call. Returns True when the thresholds were (or already are) set."""
    global _done
    if os.environ.get("GEOMESA_MALLOC_RETAIN", "1") == "0" and _done is None:
        return False
    if _done is not None:
        return _done
    try:
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        ok = bool(libc.mallopt(_M_TRIM_THRESHOLD, 2**31 - 1))
        ok = bool(libc.mallopt(_M_MMAP_THRESHOLD, 2**31 - 1)) and ok
        _done = ok
        if ok:
            # one line so operators can attribute pinned RSS to this knob
            # (irreversible for the process; GEOMESA_MALLOC_RETAIN=0
            # before the first call opts out)
            import sys

            print(
                "[geomesa] malloc retain enabled: freed memory stays "
                "in-process (GEOMESA_MALLOC_RETAIN=0 to disable)",
                file=sys.stderr,
            )
    except Exception:  # noqa: BLE001 - non-glibc platforms: no-op
        _done = False
    return _done

