"""Durable telemetry: the crash-surviving flight recorder.

Everything PRs 2-15 record — the timeline ring, SLO burn state, breaker
transitions, reason-coded decision tallies, the plan-fingerprint
registry — is in-memory and dies with the process, which is precisely
the moment it matters. This module is the persistence layer under all
of it: a per-process **spool** of append-only JSONL segment files in
``<root>/_telemetry/``, fed write-behind from the TimelineSampler tick,
plus the three consumers that spend the history:

* **the spool** (``HistorySpool``) — per tick it records the timeline
  snapshot, breaker *transitions* (diffed against the previous tick),
  per-tick ``decision.*`` tallies, SLO violations with their exemplar
  trace ids, and (periodically) the per-fingerprint top-K with
  misestimate histograms. Records queue in a BOUNDED list and flush on
  the sampler-tick thread — never a query thread — under a small
  budget, span-wrapped and fault-injectable (``history.append``).
  Overflow past the queue bound drops oldest-first and counts
  ``history.dropped``: backpressure degrades the recording, never the
  serving. Segments rotate at ``geomesa.history.bytes`` (sealed with
  the store/integrity.py CRC footer) and age out after
  ``geomesa.history.ttl``; a corrupt segment quarantines-and-skips via
  the same discipline every store file uses — adjacent segments keep
  their ticks. ``geomesa.history.enabled=0`` opens no spool, creates no
  directory, and leaves the sampler hook a single attribute read.

* **the crash black box** — opening a spool writes a ``live-<pid>``
  marker; a clean close (atexit or explicit) dumps the trace ring, the
  slow-query tail, and breaker/admission snapshots to
  ``_telemetry/blackbox-<pid>.json``, seals the active segment, and
  removes the marker. A marker whose pid is dead at the NEXT open is an
  unclean shutdown: counted ``history.unclean_start``, recorded in the
  spool, surfaced on ``GET /debug/recovery``. A ``kill -9`` leaves the
  marker (the detection) and the unsealed segment (the evidence) — the
  reader passes footer-less segments through unverified and skips torn
  trailing lines, so the pre-kill window replays.

* **fleet postmortems** — every fleet worker spools locally; the
  budget-bounded ``op_history`` RPC (parallel/fleet.py, the PR 15
  passive-observation posture) ships windowed records to
  ``GET /debug/history?s=&until=``, and ``scripts/postmortem.py``
  reconstructs the merged fleet timeline for ANY past window purely
  from disk — including from a PR 16 standby after takeover.

* **the perf-regression sentry** (``PerfSentry``) — per-fingerprint
  EWMA latency baselines over the per-tick plan deltas; a sustained
  log2 shift >= ``geomesa.sentry.threshold`` covering at least
  ``geomesa.sentry.min.events`` query events raises a reason-coded
  ``decision("sentry", "regressed")``, degrades /healthz naming the
  fingerprint, lands in the incident report, and clears with
  ``decision("sentry", "recovered")`` once latency returns under
  threshold. The first consumer that spends telemetry on a decision
  instead of a dashboard.
"""

from __future__ import annotations

import atexit
import json
import logging
import math
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu.utils import deadline
from geomesa_tpu.utils.audit import robustness_metrics

_log = logging.getLogger("geomesa_tpu.history")

TELEMETRY_DIR = "_telemetry"
SEGMENT_PREFIX = "seg-"
MARKER_PREFIX = "live-"
BLACKBOX_PREFIX = "blackbox-"

# write-behind queue bound: a wedged disk degrades the RECORDING
# (drops, counted), never the sampler thread's memory or a query
PENDING_CAP = 256
# per-flush budget: the sampler tick pays at most this for durability
# (an injected latency fault clamps to it via deadline.remaining)
FLUSH_BUDGET_S = 0.5
# per-fingerprint top-K cadence: the full rows (misestimate histograms,
# receipts) are heavy relative to a tick, so they spool periodically
PLANS_EVERY_TICKS = 30
# EWMA smoothing for the sentry's per-fingerprint latency baseline
SENTRY_ALPHA = 0.2


def history_knobs() -> Tuple[bool, Optional[int], Optional[float]]:
    """(enabled, segment_bytes, ttl_s) from the geomesa.history.* tier.

    PR 6 knob rule: explicit zeros are honored — ``history.bytes=0``
    disables size rotation (one growing active segment),
    ``history.ttl=0`` disables the retention sweep. ``None`` (returned
    as the value itself) never happens: unset falls to the defaults."""
    from geomesa_tpu.utils.config import (
        HISTORY_BYTES,
        HISTORY_ENABLED,
        HISTORY_TTL,
    )

    enabled = bool(HISTORY_ENABLED.to_bool())
    b = HISTORY_BYTES.to_bytes()
    seg_bytes = (1 << 20) if b is None else int(b)
    t = HISTORY_TTL.to_duration_s()
    ttl_s = 24 * 3600.0 if t is None else float(t)
    return enabled, seg_bytes, ttl_s


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # alive, owned by someone else
    except OSError:
        return False
    return True


# -- the perf-regression sentry ----------------------------------------------


class PerfSentry:
    """Per-fingerprint EWMA latency baselines over the spool's per-tick
    plan deltas (``utils/plans.timeline_deltas`` rows: one
    ``{fingerprint, calls, ms}`` row per hot fingerprint per tick).

    While a fingerprint is healthy its baseline tracks the EWMA of its
    per-call latency; a tick whose per-call latency sits
    ``log2(cur/baseline) >= geomesa.sentry.threshold`` accumulates its
    CALLS toward ``geomesa.sentry.min.events`` (event-weighted: one
    slow tick of 100 queries is worth 100 events, one slow stray query
    is worth 1 — a quiet store must not page anyone). Crossing the
    floor flips the fingerprint to REGRESSED: a reason-coded
    ``decision("sentry", "regressed")`` (counter + span event + plan
    tally at once) and an entry in ``regressed`` that /healthz and the
    incident report name. The baseline deliberately FREEZES while over
    threshold — an EWMA that keeps averaging would absorb the
    regression it is supposed to flag. One healthy tick clears the
    fingerprint with ``decision("sentry", "recovered")``."""

    def __init__(self):
        from geomesa_tpu.utils.config import (
            SENTRY_MIN_EVENTS,
            SENTRY_THRESHOLD,
        )

        th = SENTRY_THRESHOLD.to_float()
        self.threshold = 1.0 if th is None else float(th)
        me = SENTRY_MIN_EVENTS.to_int()
        self.min_events = 32 if me is None else int(me)
        self._baseline: Dict[str, float] = {}  # fid -> EWMA ms/call
        self._hot: Dict[str, int] = {}  # fid -> events over threshold
        self.regressed: Dict[str, Dict[str, Any]] = {}

    def observe(
        self, prows: List[Dict[str, Any]], t: float
    ) -> List[Dict[str, Any]]:
        """Feed one tick's plan-delta rows; returns sentry records to
        spool (state changes only — a steady regression is one record
        when it trips and one when it clears, not one per tick)."""
        if self.threshold <= 0:  # explicit 0 disables (knob rule)
            return []
        from geomesa_tpu.utils import audit

        events: List[Dict[str, Any]] = []
        for row in prows or ():
            fid = row.get("fingerprint")
            calls = int(row.get("calls") or 0)
            ms = float(row.get("ms") or 0.0)
            if not fid or calls <= 0:
                continue
            cur = ms / calls
            base = self._baseline.get(fid)
            if base is None:
                self._baseline[fid] = cur  # first sight primes, no verdict
                continue
            shift = math.log2(max(cur, 1e-6) / max(base, 1e-6))
            if shift >= self.threshold:
                hot = self._hot.get(fid, 0) + calls
                self._hot[fid] = hot
                if fid not in self.regressed and hot >= self.min_events:
                    info = {
                        "shift_log2": round(shift, 3),
                        "baseline_ms": round(base, 3),
                        "latency_ms": round(cur, 3),
                        "events": hot,
                        "since": t,
                    }
                    self.regressed[fid] = info
                    audit.decision(
                        "sentry",
                        "regressed",
                        fingerprint=fid,
                        shift_log2=info["shift_log2"],
                        baseline_ms=info["baseline_ms"],
                        latency_ms=info["latency_ms"],
                    )
                    events.append(
                        {"kind": "sentry", "t": t, "state": "regressed",
                         "fingerprint": fid, **info}
                    )
            else:
                self._baseline[fid] = (
                    (1.0 - SENTRY_ALPHA) * base + SENTRY_ALPHA * cur
                )
                self._hot.pop(fid, None)
                if self.regressed.pop(fid, None) is not None:
                    audit.decision(
                        "sentry", "recovered", fingerprint=fid,
                        latency_ms=round(cur, 3),
                    )
                    events.append(
                        {"kind": "sentry", "t": t, "state": "recovered",
                         "fingerprint": fid, "latency_ms": round(cur, 3)}
                    )
        return events


# -- the spool ----------------------------------------------------------------


class HistorySpool:
    """One process's durable telemetry spool under ``<root>/_telemetry``.

    ``append()`` only queues (bounded, never blocks, never raises);
    ``flush()`` — called from the sampler-tick thread, structurally
    never a query thread — writes the queue to the active segment under
    the ``history.append`` span/fault-point/deadline discipline. A
    failed flush re-queues (bounded by the same cap), so a transient
    disk fault loses nothing and a dead disk degrades to counted
    drops."""

    def __init__(self, root: str, owner: str = ""):
        self.root = os.path.abspath(root)
        self.dir = os.path.join(self.root, TELEMETRY_DIR)
        self.owner = owner or f"pid{os.getpid()}"
        _enabled, self.seg_bytes, self.ttl_s = history_knobs()
        self._lock = threading.Lock()
        self._pending: List[Dict[str, Any]] = []
        self._active: Optional[str] = None
        self._active_size = 0
        self._prev_breakers: Dict[str, str] = {}
        self._ticks = 0
        self._closed = False
        self._last_written: Optional[str] = None
        self.sentry = PerfSentry()
        self.unclean: List[Dict[str, Any]] = []
        os.makedirs(self.dir, exist_ok=True)
        self._scan_unclean()
        self._marker = os.path.join(
            self.dir, f"{MARKER_PREFIX}{os.getpid()}"
        )
        with open(self._marker, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(
                {"pid": os.getpid(), "owner": self.owner, "t": time.time()}
            ))
        atexit.register(self._atexit)

    # -- unclean-start detection / black box ---------------------------------

    def _scan_unclean(self) -> None:
        """A ``live-<pid>`` marker whose pid is dead means that process
        never closed its spool: an unclean shutdown (kill -9, OOM, power
        loss). Counted, recorded, and the stale marker consumed so one
        crash reports once — the unsealed segment it left behind stays,
        that is the evidence the postmortem replays."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in sorted(names):
            if not name.startswith(MARKER_PREFIX):
                continue
            try:
                pid = int(name[len(MARKER_PREFIX):])
            except ValueError:
                continue
            if pid == os.getpid() or _pid_alive(pid):
                continue
            path = os.path.join(self.dir, name)
            info: Dict[str, Any] = {"pid": pid}
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    info.update(json.loads(fh.read()))
            except (OSError, ValueError):
                pass
            info["blackbox"] = os.path.exists(
                os.path.join(self.dir, f"{BLACKBOX_PREFIX}{pid}.json")
            )
            robustness_metrics().inc("history.unclean_start")
            self.unclean.append(info)
            self.append({
                "kind": "unclean_start", "t": time.time(),
                "owner": self.owner, "dead": info,
            })
            try:
                os.remove(path)
            except OSError:
                pass

    def _blackbox_payload(self) -> Dict[str, Any]:
        from geomesa_tpu.utils import trace as _trace
        from geomesa_tpu.utils.audit import slow_query_tail
        from geomesa_tpu.utils.breaker import peek_states

        out: Dict[str, Any] = {
            "t": time.time(),
            "pid": os.getpid(),
            "owner": self.owner,
            "breakers": peek_states(),
            "slow_queries": slow_query_tail(50),
        }
        try:
            out["traces"] = [
                sp.to_dict() for sp in _trace.blackbox_traces(20)
            ]
        except Exception as e:  # noqa: BLE001 - a bad span must not lose the box
            out["traces"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def close(self, blackbox: bool = True) -> None:
        """Clean shutdown: drain the queue, dump the black box, seal the
        active segment (CRC footer — replay verifies it), remove the
        live marker. Idempotent; also the atexit path."""
        from geomesa_tpu.store import integrity

        with self._lock:
            if self._closed:
                return
            self._closed = True
            batch, self._pending = self._pending, []
            active = self._active
            self._active = None
        try:
            if batch:
                self._write(batch)
                active = active or self._last_written
        except OSError:
            robustness_metrics().inc("history.append.errors")
        if blackbox:
            try:
                integrity.durable_write(
                    os.path.join(
                        self.dir, f"{BLACKBOX_PREFIX}{os.getpid()}.json"
                    ),
                    json.dumps(
                        self._blackbox_payload(), default=str
                    ).encode("utf-8"),
                )
            except Exception:  # noqa: BLE001 - shutdown path must not raise
                _log.exception("blackbox dump failed")
        try:
            if active and os.path.exists(active):
                integrity.append_crc_footer(active)
            integrity.fsync_dir(self.dir)
        except OSError:
            pass
        try:
            os.remove(self._marker)
        except OSError:
            pass

    def _atexit(self) -> None:
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    # -- write-behind ---------------------------------------------------------

    def append(self, record: Dict[str, Any]) -> None:
        """Queue one record (bounded; DROPS past the cap, counted
        ``history.dropped``). Safe from any thread; never blocks on
        I/O, never raises — this is the only call a non-tick thread
        ever makes into the spool."""
        with self._lock:
            if self._closed or len(self._pending) >= PENDING_CAP:
                if not self._closed:
                    robustness_metrics().inc("history.dropped")
                return
            self._pending.append(record)

    def flush(self) -> int:
        """Drain the queue to the active segment: span-wrapped,
        fault-injectable, and budget-bounded — a wedged disk costs the
        sampler tick at most ``FLUSH_BUDGET_S`` and the batch re-queues
        (bounded) for the next tick. Returns records written."""
        from geomesa_tpu.utils import faults, trace

        with self._lock:
            if self._closed or not self._pending:
                return 0
            batch, self._pending = self._pending, []
        try:
            with trace.span("history.append") as sp:
                with deadline.budget(FLUSH_BUDGET_S):
                    deadline.check("history.append")
                    faults.fault_point("history.append")
                    n = self._write(batch)
                sp.set_attr("records", n)
            return n
        except Exception as e:  # noqa: BLE001 - recording degrades, never raises
            robustness_metrics().inc("history.append.errors")
            _log.debug("history flush failed, re-queueing: %s", e)
            with self._lock:
                merged = batch + self._pending
                dropped = len(merged) - PENDING_CAP
                if dropped > 0:
                    # oldest-first drop: the tail is closest to "now",
                    # which is what a postmortem wants most
                    merged = merged[dropped:]
                    robustness_metrics().inc("history.dropped", dropped)
                self._pending = merged
            return 0

    def _write(self, batch: List[Dict[str, Any]]) -> int:
        """Append the batch to the active segment; rotate + sweep when
        the size bound trips. Single-writer by construction (only the
        tick thread and close() call this, close() after _closed)."""
        if self._active is None:
            self._active = os.path.join(
                self.dir,
                f"{SEGMENT_PREFIX}{int(time.time() * 1000)}"
                f"-{os.getpid()}.jsonl",
            )
            self._active_size = 0
        data = b"".join(
            json.dumps(rec, default=str).encode("utf-8") + b"\n"
            for rec in batch
        )
        with open(self._active, "ab") as fh:
            fh.write(data)
        self._active_size += len(data)
        self._last_written = self._active
        if self.seg_bytes and self._active_size >= self.seg_bytes:
            self._rotate()
        return len(batch)

    def _rotate(self) -> None:
        """Seal the active segment (CRC footer: the reader VERIFIES
        sealed segments; a torn or bit-flipped one quarantines) and
        sweep expired ones. The next flush opens a fresh segment."""
        from geomesa_tpu.store import integrity

        sealed, self._active = self._active, None
        self._active_size = 0
        try:
            integrity.append_crc_footer(sealed)
            integrity.fsync_dir(self.dir)
        except OSError:
            robustness_metrics().inc("history.append.errors")
        robustness_metrics().inc("history.segments.sealed")
        self._sweep()

    def _sweep(self) -> None:
        """Age out sealed segments past ``history.ttl`` (explicit 0
        disables). mtime-based: a segment's mtime is its LAST write, so
        a segment only expires once everything in it is stale."""
        if not self.ttl_s:
            return
        cutoff = time.time() - self.ttl_s
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if not (name.startswith(SEGMENT_PREFIX)
                    and name.endswith(".jsonl")):
                continue
            path = os.path.join(self.dir, name)
            if path == self._active:
                continue
            try:
                if os.stat(path).st_mtime < cutoff:
                    os.remove(path)
                    robustness_metrics().inc("history.segments.expired")
            except OSError:
                continue

    # -- the per-tick feed ----------------------------------------------------

    def on_tick(self, snap: Dict[str, Any], store: Any = None) -> None:
        """The write-behind feed, called from the TimelineSampler tick
        (coordinator) or the ``op_timeline`` on-demand tick (fleet
        worker) AFTER the in-memory ring append — the ring is the
        source of truth, the spool is its shadow. Builds this tick's
        durable records, runs the sentry, flushes."""
        if self._closed or not snap:
            return
        t = float(snap.get("t") or time.time())
        self._ticks += 1
        self.append({"kind": "tick", "t": t, "owner": self.owner,
                     "tick": snap})
        # breaker TRANSITIONS, not states: the tick record already has
        # the full state map, this one answers "when did it flip"
        cur = dict(snap.get("breakers") or {})
        changed = {
            name: [self._prev_breakers.get(name, "closed"), state]
            for name, state in cur.items()
            if self._prev_breakers.get(name, "closed") != state
        }
        if changed:
            self.append({"kind": "breaker", "t": t, "changed": changed})
        self._prev_breakers = cur
        # reason-coded decision tallies: the tick counters are already
        # per-tick deltas, so the decision.* slice IS this tick's tally
        tallies = {
            k: v for k, v in (snap.get("counters") or {}).items()
            if k.startswith("decision.")
        }
        if tallies:
            self.append({"kind": "decision", "t": t, "tallies": tallies})
        if store is not None:
            self._record_slo(t, store)
            if self._ticks % PLANS_EVERY_TICKS == 1:
                self._record_plans(t, store)
                self._record_tenants(t, store)
        for ev in self.sentry.observe(snap.get("plans") or [], t):
            self.append(ev)
        self.flush()

    def _record_slo(self, t: float, store: Any) -> None:
        """SLO violations with exemplar trace ids — only while
        violating (a healthy tick spools nothing), and only against an
        engine that ALREADY exists (the sampler must never be what
        creates telemetry state — the engine_for create=False rule)."""
        try:
            from geomesa_tpu.utils import slo as _slo

            eng = _slo.engine_for(store, create=False)
            if eng is None:
                return
            rec = _slo.violation_record(eng)
            if rec:
                self.append({"kind": "slo", "t": t, **rec})
        except Exception:  # noqa: BLE001 - recording must not kill the tick
            _log.debug("slo history record failed", exc_info=True)

    def _record_plans(self, t: float, store: Any) -> None:
        """Periodic per-fingerprint top-K with misestimate histograms —
        the recorded statistics the adaptive-selection thesis needs to
        outlive the process that recorded them."""
        try:
            preg = getattr(store, "_plans", None)
            if preg is None:
                return
            from geomesa_tpu.utils import plans as _plans

            rows = _plans.history_rows(preg, n=10)
            if rows:
                self.append({"kind": "plans", "t": t, "rows": rows})
        except Exception:  # noqa: BLE001 - recording must not kill the tick
            _log.debug("plans history record failed", exc_info=True)

    def _record_tenants(self, t: float, store: Any) -> None:
        """Periodic per-tenant cost table (utils/tenants.py) — who was
        burning the store, durable; postmortems fold it around a kill
        instant the same way they fold the plans table."""
        try:
            treg = getattr(store, "_tenants", None)
            if treg is None:
                return
            from geomesa_tpu.utils import tenants as _tenants

            rows = _tenants.history_rows(treg, n=10)
            if rows:
                self.append({"kind": "tenants", "t": t, "rows": rows})
        except Exception:  # noqa: BLE001 - recording must not kill the tick
            _log.debug("tenants history record failed", exc_info=True)

    # -- introspection --------------------------------------------------------

    def segments(self) -> List[str]:
        try:
            return sorted(
                n for n in os.listdir(self.dir)
                if n.startswith(SEGMENT_PREFIX) and n.endswith(".jsonl")
            )
        except OSError:
            return []

    def info(self) -> Dict[str, Any]:
        """The /debug/recovery ``history`` block."""
        counters, _g, _t, _tt = robustness_metrics().snapshot()
        return {
            "dir": self.dir,
            "owner": self.owner,
            "segments": len(self.segments()),
            "pending": len(self._pending),
            "unclean_starts": list(self.unclean),
            "dropped": counters.get("history.dropped", 0),
            "regressed": dict(self.sentry.regressed),
        }

    def read(
        self,
        s: Optional[float] = None,
        until: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> Tuple[List[Dict[str, Any]], bool]:
        self.flush()
        return read_records(self.root, s=s, until=until, limit=limit)


# -- the reader (works with no live spool: postmortems read dead roots) -------


def read_records(
    root: str,
    s: Optional[float] = None,
    until: Optional[float] = None,
    limit: Optional[int] = None,
    prefix: str = SEGMENT_PREFIX,
    counter_ns: str = "history",
) -> Tuple[List[Dict[str, Any]], bool]:
    """Every spool record under ``<root>/_telemetry`` with
    ``s <= t <= until`` (both optional), oldest first; returns
    ``(records, truncated)``. Disk-only — a SIGKILLed or long-dead
    process's spool reads the same as a live one.

    The integrity discipline (store/integrity.py): sealed segments CRC-
    verify — a corrupt one is quarantined and SKIPPED (counted
    ``<ns>.segments.corrupt``), adjacent segments keep their ticks.
    Footer-less segments (the active one, or one a kill -9 orphaned)
    pass through unverified; a torn trailing line skips per-line
    (counted ``<ns>.torn``) and every parseable line before it
    survives. ``prefix``/``counter_ns`` select the segment KIND — the
    workload-capture spool (utils/workload.py, ``wl-`` segments) reads
    through this same verified path under its own counters."""
    from geomesa_tpu.store import integrity

    d = os.path.join(root, TELEMETRY_DIR)
    out: List[Dict[str, Any]] = []
    truncated = False
    if not os.path.isdir(d):
        return out, truncated
    cap = None if limit is None else max(0, int(limit))
    for name in sorted(os.listdir(d)):
        if not (name.startswith(prefix) and name.endswith(".jsonl")):
            continue
        path = os.path.join(d, name)
        try:
            data = integrity.read_verified(path)
        except integrity.CorruptFileError:
            robustness_metrics().inc(f"{counter_ns}.segments.corrupt")
            integrity.quarantine(path)
            continue
        except OSError:
            continue
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                robustness_metrics().inc(f"{counter_ns}.torn")
                continue
            if not isinstance(rec, dict):
                robustness_metrics().inc(f"{counter_ns}.torn")
                continue
            t = rec.get("t")
            if not isinstance(t, (int, float)):
                continue
            if s is not None and t < float(s):
                continue
            if until is not None and t > float(until):
                continue
            if cap is not None and len(out) >= cap:
                truncated = True
                break
            out.append(rec)
        if truncated:
            break
    out.sort(key=lambda r: r.get("t", 0.0))
    return out, truncated


def blackboxes(root: str) -> List[Dict[str, Any]]:
    """Every ``blackbox-<pid>.json`` under the root's spool, parsed."""
    d = os.path.join(root, TELEMETRY_DIR)
    out: List[Dict[str, Any]] = []
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not (name.startswith(BLACKBOX_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name), "r", encoding="utf-8") as fh:
                box = json.loads(fh.read())
        except (OSError, ValueError):
            continue
        if isinstance(box, dict):
            box["file"] = name
            out.append(box)
    return out


def stale_markers(root: str) -> List[int]:
    """Pids of dead processes whose live markers were never consumed —
    the disk-only unclean-shutdown signal a postmortem reads without a
    process having restarted yet."""
    d = os.path.join(root, TELEMETRY_DIR)
    out: List[int] = []
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not name.startswith(MARKER_PREFIX):
            continue
        try:
            pid = int(name[len(MARKER_PREFIX):])
        except ValueError:
            continue
        if not _pid_alive(pid):
            out.append(pid)
    return out


# -- per-store spools (the sampler_for arrangement) ---------------------------

_SPOOLS: "weakref.WeakKeyDictionary[Any, HistorySpool]" = (
    weakref.WeakKeyDictionary()
)
_SPOOLS_LOCK = threading.Lock()


def open_spool(root: str, owner: str = "") -> Optional[HistorySpool]:
    """A spool at an explicit root (fleet workers), or None when
    ``geomesa.history.enabled=0`` / the directory cannot be created —
    disabled history must cost nothing and break nothing."""
    enabled, _b, _t = history_knobs()
    if not enabled or not root:
        return None
    try:
        return HistorySpool(root, owner=owner)
    except OSError:
        _log.warning("history spool unavailable at %s", root, exc_info=True)
        return None


def spool_for(store: Any, create: bool = True) -> Optional[HistorySpool]:
    """The store's spool, keyed weakly like timeline.sampler_for; only
    stores with a durable ``root`` (fleet coordinators, workers, fs
    stores that grow one) can spool — everything else answers None and
    the sampler hook stays a no-op attribute read."""
    root = getattr(store, "root", None)
    if not isinstance(root, str) or not root:
        return None
    with _SPOOLS_LOCK:
        got = _SPOOLS.get(store)
        if got is not None or not create:
            return got
        sp = open_spool(root, owner=type(store).__name__)
        if sp is not None:
            _SPOOLS[store] = sp
        return sp


def sentry_regressions(store: Any) -> Dict[str, Dict[str, Any]]:
    """The /healthz hook: currently-regressed fingerprints, by
    fingerprint. create=False — a health probe must never be what opens
    the spool (the engine_for posture)."""
    sp = spool_for(store, create=False)
    return {} if sp is None else dict(sp.sentry.regressed)


def recovery_info(store: Any) -> Optional[Dict[str, Any]]:
    """The /debug/recovery ``history`` block, or None when no spool."""
    sp = spool_for(store, create=False)
    return None if sp is None else sp.info()
