"""Per-tenant cost metering: who is burning the fleet, end to end.

Receipts exist per query (PR 3) and per plan fingerprint (PR 11), but
nothing attributes cost to a *client*. This module closes that gap: a
tenant label travels with the query (query hint ``tenant``; web.py maps
the ``X-Geomesa-Tenant`` header into it, the hint winning when both are
present; absent = ``"anon"``) and every served query / join / aggregate
/ stream folds into a fixed-memory top-K LRU of per-tenant aggregates —
the ``utils/plans.py`` registry discipline applied to the *who* axis:

* calls + outcome counts (ok / timeout / shed / error) and the ``bad``
  total the per-tenant SLO availability burn folds;
* a latency timer per tenant through ``audit.MetricsRegistry`` — the
  PR 10 per-tick histograms and trace-linked exemplars come free;
* rows returned and cost-receipt sums (recompiles, h2d/d2h bytes, pad);
* per-class splits (query / join / aggregate / stream): which *kind* of
  traffic each tenant is.

Free when off: ``geomesa.tenants.enabled=0`` reduces the hot-path hook
to a single cached module-flag read (the plans posture). ``max`` bounds
tenants per registry; past it the coldest evicts (counted, its timer
dropped) — an adversarial flood of labels costs fixed memory.

Surfaces: ``GET /debug/tenants`` (the /debug/plans 400/clamp/sort
contract), the ``tenants`` section of ``GET /debug/report``, per-tick
tenant deltas in the timeline (which per-tenant SLO burn evaluates —
a violation names ``<slo>@tenant:<label>`` on /healthz), periodic
durable ``tenants`` records in the history spool, and the fleet rollup:
the label crosses the wire in the query hints, every worker keeps its
own registry, and ``tenants_rollup()`` merges full capped registries
exactly like ``plans_rollup()`` (weighted-mean merge, never top-n of
top-n).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu.utils.audit import MetricsRegistry, histogram_summary

# the default label: queries that carry no tenant hint/header still
# meter (conservation — per-tenant sums must equal store-level counts)
ANON = "anon"
# labels are operator-facing identifiers, not payloads: bound them so a
# hostile header cannot bloat registries, metric names, or SLO verdicts
MAX_LABEL = 64

# -- the flag -----------------------------------------------------------------

_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """The hot-path gate: one module-global read once resolved."""
    e = _ENABLED
    if e is None:
        return _resolve()
    return e


def _resolve() -> bool:
    global _ENABLED
    from geomesa_tpu.utils.config import TENANTS_ENABLED

    _ENABLED = bool(TENANTS_ENABLED.to_bool())
    return _ENABLED


def set_enabled(on: Optional[bool]) -> None:
    """Flip the cached flag (``None`` re-resolves on the next read)."""
    global _ENABLED
    _ENABLED = None if on is None else bool(on)


def tenants_knobs() -> Tuple[bool, int]:
    """(enabled, max_tenants) from the geomesa.tenants.* tier."""
    from geomesa_tpu.utils.config import TENANTS_MAX

    cap = TENANTS_MAX.to_int()
    return enabled(), 64 if cap is None or cap <= 0 else cap


def tenant_of(query: Any) -> str:
    """The query's tenant label: the ``tenant`` hint, cleaned and
    bounded, else ``"anon"``. Accepts any duck-typed query (or None)."""
    hints = getattr(query, "hints", None)
    label = hints.get("tenant") if isinstance(hints, dict) else None
    return clean_label(label)


def clean_label(label: Any) -> str:
    """Normalize one externally-supplied label: non-string / blank /
    whitespace-only fall to ``"anon"``; the rest strip + truncate."""
    if not isinstance(label, str):
        return ANON
    label = label.strip()
    if not label:
        return ANON
    return label[:MAX_LABEL]


# -- per-tenant default priority ----------------------------------------------
#
# The middle rung of the priority ladder (utils/admission.py classify):
# an explicit `geomesa.query.priority` hint wins, then the query's
# tenant looks up here, then `geomesa.priority.default`. The map knob is
# "tenantA=critical,tenantB=background" — parsed once and cached (the
# flag posture above), so the per-admit lookup is one dict get.

_PRIORITY_MAP: Optional[Dict[str, str]] = None


def default_priority(tenant: str) -> Optional[str]:
    """The tenant's configured default priority class, or None when the
    map has no entry (the caller falls through to the global default)."""
    m = _PRIORITY_MAP
    if m is None:
        m = _resolve_priority_map()
    return m.get(tenant)


def _resolve_priority_map() -> Dict[str, str]:
    global _PRIORITY_MAP
    from geomesa_tpu.utils.config import TENANTS_PRIORITY

    raw = TENANTS_PRIORITY.get()
    out: Dict[str, str] = {}
    if raw:
        from geomesa_tpu.utils.admission import PRIORITIES

        for pair in str(raw).split(","):
            label, _, cls = pair.partition("=")
            label = clean_label(label)
            cls = cls.strip().lower()
            if label != ANON and cls in PRIORITIES:
                out[label] = cls
    _PRIORITY_MAP = out
    return out


def reset_priority_map() -> None:
    """Drop the cached map (re-parsed on the next lookup) — for tests
    and config reloads that flip ``geomesa.tenants.priority``."""
    global _PRIORITY_MAP
    _PRIORITY_MAP = None


# -- the registry -------------------------------------------------------------


class TenantEntry:
    """One tenant's aggregates (mutated under the registry lock)."""

    __slots__ = (
        "label", "calls", "outcomes", "bad", "rows", "total_s", "last_ms",
        "recompiles", "h2d_bytes", "d2h_bytes", "pad_ratio_sum",
        "pad_calls", "classes",
    )

    def __init__(self, label: str):
        self.label = label
        self.calls = 0
        self.outcomes: Dict[str, int] = {}
        self.bad = 0  # non-ok outcomes: the SLO availability numerator
        self.rows = 0
        self.total_s = 0.0
        self.last_ms = 0.0
        self.recompiles = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.pad_ratio_sum = 0.0
        self.pad_calls = 0
        self.classes: Dict[str, Dict[str, Any]] = {}

    def row(self) -> Dict[str, Any]:
        return {
            "tenant": self.label,
            "calls": self.calls,
            "outcomes": dict(self.outcomes),
            "bad": self.bad,
            "rows": self.rows,
            "total_ms": round(self.total_s * 1000.0, 3),
            "last_ms": round(self.last_ms, 3),
            "receipt": {
                "recompiles": self.recompiles,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "pad_ratio_mean": round(
                    self.pad_ratio_sum / max(self.pad_calls, 1), 4
                ),
                "pad_calls": self.pad_calls,
            },
            "classes": {
                k: {"calls": v["calls"],
                    "ms": round(v["s"] * 1000.0, 3),
                    "bad": v["bad"]}
                for k, v in sorted(self.classes.items())
            },
        }


_SORTS = {
    "time": lambda r: r["total_ms"],
    "calls": lambda r: r["calls"],
    "rows": lambda r: r["rows"],
    "bad": lambda r: r["bad"],
}
# the public sort-key whitelist (web.py validates ?sort= against THIS —
# the utils/plans.SORTS arrangement, no shadow copy to drift)
SORTS = tuple(_SORTS)


class TenantRegistry:
    """Fixed-memory top-K LRU of per-tenant aggregates (one per store;
    a ShardWorker / fleet worker shares ONE across its partition
    sub-stores so the rollup is one read). Latency rides
    ``self.metrics`` timers named ``tenant.<label>`` — the shared
    MetricsRegistry reservoir/exemplar machinery, dropped with the
    entry on LRU eviction so memory stays bounded by the cap alone."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = tenants_knobs()[1] if cap is None else int(cap)
        self.metrics = MetricsRegistry()
        self._entries: "OrderedDict[str, TenantEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.evicted = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def observe(
        self,
        label: str,
        cls: str,
        *,
        outcome: str = "ok",
        duration_s: float = 0.0,
        rows: int = 0,
        receipt: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Fold one finished request into its tenant (LRU-bumped;
        evicts the coldest entry past the cap)."""
        label = clean_label(label)
        dropped = None
        with self._lock:
            e = self._entries.get(label)
            if e is None:
                e = TenantEntry(label)
                self._entries[label] = e
                if len(self._entries) > self.cap:
                    _k, dropped = self._entries.popitem(last=False)
                    self.evicted += 1
            else:
                self._entries.move_to_end(label)
            e.calls += 1
            e.outcomes[outcome] = e.outcomes.get(outcome, 0) + 1
            if outcome != "ok":
                e.bad += 1
            e.rows += int(rows)
            e.total_s += float(duration_s)
            e.last_ms = float(duration_s) * 1000.0
            if receipt:
                e.recompiles += int(receipt.get("recompiles", 0))
                e.h2d_bytes += int(receipt.get("h2d_bytes", 0))
                e.d2h_bytes += int(receipt.get("d2h_bytes", 0))
                pr = float(receipt.get("pad_ratio", 0.0))
                if pr > 0.0:
                    e.pad_ratio_sum += pr
                    e.pad_calls += 1
            c = e.classes.get(cls)
            if c is None:
                c = e.classes[cls] = {"calls": 0, "s": 0.0, "bad": 0}
            c["calls"] += 1
            c["s"] += float(duration_s)
            if outcome != "ok":
                # per-class bad split: the per-tenant SLO burn folds a
                # spec's OWN class, not the tenant's mixed traffic
                c["bad"] += 1
        if dropped is not None:
            self.metrics.drop_timer(f"tenant.{dropped.label}")
        # the timer update sits OUTSIDE the registry lock (the
        # PlanRegistry ordering rule: registry lock, then metrics lock)
        self.metrics.update_timer(f"tenant.{label}", float(duration_s))

    # -- reads ---------------------------------------------------------------

    def rows(self, sort: str = "time", n: int = 20) -> List[Dict[str, Any]]:
        """Top ``n`` tenant rows by ``sort`` (time | calls | rows |
        bad), latency summaries and trace-linked exemplars attached."""
        if sort not in _SORTS:
            raise ValueError(
                f"unknown sort {sort!r} (one of {sorted(_SORTS)})"
            )
        with self._lock:
            rows = [e.row() for e in self._entries.values()]
        rows.sort(key=_SORTS[sort], reverse=True)
        rows = rows[: max(0, int(n))]
        _c, _g, timers, totals = self.metrics.snapshot()
        for r in rows:
            vals = timers.get(f"tenant.{r['tenant']}")
            if vals:
                r["latency"] = histogram_summary(
                    vals,
                    total_count=totals.get(
                        f"tenant.{r['tenant']}", (None,)
                    )[0],
                )
            ex = self.metrics.exemplars(f"tenant.{r['tenant']}")
            if ex and ex.get("buckets"):
                b = max(ex["buckets"])
                s, tid, wall = ex["buckets"][b]
                if tid:
                    r["worst_exemplar"] = {
                        "ms": round(s * 1000.0, 3),
                        "trace_id": tid,
                        "date_ms": int(wall),
                    }
        return rows

    def top(self, n: int = 5) -> List[Dict[str, Any]]:
        """Compact per-shard/timeline summary: the ``n`` hottest
        tenants by total time."""
        with self._lock:
            es = sorted(
                self._entries.values(), key=lambda e: e.total_s,
                reverse=True,
            )[: max(0, int(n))]
            return [
                {
                    "tenant": e.label,
                    "calls": e.calls,
                    "bad": e.bad,
                    "rows": e.rows,
                    "total_ms": round(e.total_s * 1000.0, 3),
                }
                for e in es
            ]

    def totals(self) -> Dict[str, tuple]:
        """{label: (calls, total_s, bad, {cls: (calls, bad)})} — the
        timeline sampler diffs consecutive reads into per-tick tenant
        deltas (which the per-tenant SLO burn folds, per class)."""
        with self._lock:
            return {
                e.label: (
                    e.calls, e.total_s, e.bad,
                    {k: (v["calls"], v["bad"])
                     for k, v in e.classes.items()},
                )
                for e in self._entries.values()
            }

    def payload(self, sort: str = "time", n: int = 20) -> Dict[str, Any]:
        """The GET /debug/tenants body (single-store edition; the
        sharded coordinator wraps this with its rollup)."""
        return {
            "enabled": enabled(),
            "sort": sort,
            "count": len(self),
            "evicted": self.evicted,
            "tenants": self.rows(sort=sort, n=n),
        }


def timeline_deltas(
    registry: Optional[TenantRegistry],
    prev: Dict[str, tuple],
    n: int = 5,
) -> Tuple[Dict[str, tuple], List[Dict[str, Any]]]:
    """One timeline tick's tenant deltas: (new_prev, rows) — "who was
    hot THIS second", with per-class call/bad splits so the per-tenant
    SLO availability burn folds a spec's OWN class. Pure reads; an
    absent registry returns no rows."""
    if registry is None:
        return prev, []
    now = registry.totals()
    rows = []
    for label, (calls, total_s, bad, classes) in now.items():
        pc, ps, pb, pcls = prev.get(label, (0, 0.0, 0, {}))
        dc = calls - pc
        if dc <= 0:
            continue
        dcls = {}
        for k, (cc, cb) in classes.items():
            oc, ob = pcls.get(k, (0, 0))
            if cc - oc > 0:
                dcls[k] = {"calls": cc - oc, "bad": cb - ob}
        rows.append({
            "tenant": label,
            "calls": dc,
            "ms": round((total_s - ps) * 1000.0, 3),
            "bad": bad - pb,
            "classes": dcls,
        })
    rows.sort(key=lambda r: r["ms"], reverse=True)
    return now, rows[: max(0, int(n))]


def history_rows(
    registry: Optional[TenantRegistry], n: int = 10
) -> List[Dict[str, Any]]:
    """The durable-spool edition of the top-K (utils/history.py
    ``tenants`` records): cumulative per-tenant calls / outcomes /
    latency / rows / receipt — what a postmortem folds around a kill
    instant. A slice of ``rows()``: exemplar pointers stay in memory."""
    if registry is None:
        return []
    out = []
    for r in registry.rows(sort="time", n=n):
        out.append({
            "tenant": r["tenant"],
            "calls": r["calls"],
            "outcomes": r["outcomes"],
            "bad": r["bad"],
            "rows": r["rows"],
            "total_ms": r["total_ms"],
            "receipt": r["receipt"],
            "classes": r["classes"],
        })
    return out


def merge_rows(row_lists: List[List[Dict[str, Any]]]) -> List[Dict[str, Any]]:
    """Merge tenant rows from several registries (the fleet rollup):
    numeric aggregates sum by label and the pad-ratio mean is
    recomputed as an EXACT weighted mean from ``mean * count`` — the
    utils/plans.merge_rows contract. Latency summaries and exemplars
    are per-source and dropped (percentile reservoirs do not merge)."""
    out: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    for rows in row_lists:
        for r in rows:
            label = r["tenant"]
            m = out.get(label)
            if m is None:
                m = {k: v for k, v in r.items()
                     if k not in ("latency", "worst_exemplar")}
                m["outcomes"] = dict(r.get("outcomes", {}))
                m["receipt"] = dict(r["receipt"])
                m["classes"] = {
                    k: dict(v) for k, v in r.get("classes", {}).items()
                }
                out[label] = m
                continue
            for k in ("calls", "bad", "rows"):
                m[k] += r.get(k, 0)
            m["total_ms"] = round(m["total_ms"] + r["total_ms"], 3)
            for k, v in r.get("outcomes", {}).items():
                m["outcomes"][k] = m["outcomes"].get(k, 0) + v
            for k, v in r.get("classes", {}).items():
                c = m["classes"].get(k)
                if c is None:
                    m["classes"][k] = dict(v)
                else:
                    c["calls"] += v.get("calls", 0)
                    c["ms"] = round(c.get("ms", 0.0) + v.get("ms", 0.0), 3)
                    c["bad"] = c.get("bad", 0) + v.get("bad", 0)
            mr, rr = m["receipt"], r["receipt"]
            pad_sum = (
                mr["pad_ratio_mean"] * mr.get("pad_calls", 0)
                + rr["pad_ratio_mean"] * rr.get("pad_calls", 0)
            )
            mr["pad_calls"] = mr.get("pad_calls", 0) + rr.get("pad_calls", 0)
            mr["pad_ratio_mean"] = round(
                pad_sum / max(mr["pad_calls"], 1), 4
            )
            for k in ("recompiles", "h2d_bytes", "d2h_bytes"):
                mr[k] += rr.get(k, 0)
    merged = list(out.values())
    merged.sort(key=lambda r: r["total_ms"], reverse=True)
    return merged
