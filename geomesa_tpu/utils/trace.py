"""End-to-end query tracing: a lightweight span-tree tracer.

The reference attributes per-query latency through Dropwizard timers and
MethodProfiling (utils/stats/MethodProfiling.scala:1-222), which answers
"how slow is planning on average" but not "where did THIS query spend its
time". GPU/TPU engines need the per-stage split (kernel vs transfer vs
host post-filter — arxiv 2203.14362 §5) to attribute anything, so this
module provides what process-wide counters cannot: one tree of timed
spans per query, from plan through range decomposition, block scans,
device dispatch/fetch (or the degradation event) to the post-filter.

Design constraints, in order:

1. **Free when off.** With no exporter installed and no active trace,
   ``span()`` returns a shared no-op singleton — two reads and no
   allocation — so the hooks can sit on per-block and per-RPC paths
   (the fault_point posture, utils/faults.py:44-47).
2. **Context propagation.** The active span lives in a ``contextvars``
   ContextVar, so nesting needs no plumbing and ``wrap()`` carries a
   trace across the executor's / server's worker threads.
3. **Whole trees, not span streams.** Exporters receive the ROOT span
   once it closes, children attached — consumers (the slow-query log,
   /debug/traces, tests) always see a complete tree and never splice.

Usage::

    from geomesa_tpu.utils import trace

    with trace.exporting(trace.InMemoryTraceExporter()) as ring:
        with trace.span("query", type="gdelt") as root:
            with trace.span("plan"):
                ...
            trace.event("degrade.device_to_host", error="tunnel died")
    ring.traces[-1].render()

Cross-process correlation: ``current_trace_id()`` rides in the netlog
message envelope, and the broker opens its server-side spans with that
``trace_id`` — one id joins client and broker work (stream/netlog.py).
"""

from __future__ import annotations

import contextvars
import json
import logging
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed operation: name, [start, end), attributes, point-in-time
    events, and child spans. Times are perf_counter-based; ``start_ms``
    is the epoch wall clock for log correlation."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ms",
        "duration_ms", "attributes", "events", "children", "_t0",
    )

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start_ms = time.time() * 1000.0
        self.duration_ms: float = 0.0
        self.attributes: Dict[str, Any] = {}
        self.events: List[Dict[str, Any]] = []
        self.children: List["Span"] = []
        self._t0 = time.perf_counter()

    # real spans record; the no-op singleton overrides this to False so
    # callers can skip computing expensive attribute values
    recording = True

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        ev: Dict[str, Any] = {
            "name": name,
            "t_ms": (time.perf_counter() - self._t0) * 1000.0,
        }
        if attrs:
            ev.update(attrs)
        self.events.append(ev)
        return self

    def finish(self) -> None:
        self.duration_ms = (time.perf_counter() - self._t0) * 1000.0

    @property
    def self_time_ms(self) -> float:
        """Duration minus DIRECT children's durations (time attributable
        to this span's own work)."""
        return max(
            0.0, self.duration_ms - sum(c.duration_ms for c in self.children)
        )

    def walk(self):
        """Yield this span and every descendant, depth-first."""
        yield self
        for c in self.children:
            yield from c.walk()

    def find(self, name: str) -> List["Span"]:
        return [s for s in self.walk() if s.name == name]

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start_ms": self.start_ms,
            "duration_ms": self.duration_ms,
        }
        if self.parent_id:
            out["parent_id"] = self.parent_id
        if self.attributes:
            out["attributes"] = dict(self.attributes)
        if self.events:
            out["events"] = list(self.events)
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Span":
        """Inverse of ``to_dict`` — rebuilds a span tree from its wire
        form (the fleet trace-stitching trailer, parallel/fleet.py).
        Ids and timings are kept verbatim; the caller re-anchors wall
        times via ``graft`` (a remote clock is never trusted as-is)."""
        sp = cls.__new__(cls)
        sp.name = str(d.get("name", ""))
        sp.trace_id = str(d.get("trace_id", ""))
        sp.span_id = str(d.get("span_id") or _new_id())
        sp.parent_id = d.get("parent_id")
        sp.start_ms = float(d.get("start_ms", 0.0))
        sp.duration_ms = float(d.get("duration_ms", 0.0))
        sp.attributes = dict(d.get("attributes") or {})
        sp.events = list(d.get("events") or [])
        sp.children = [cls.from_dict(c) for c in d.get("children") or ()]
        sp._t0 = 0.0  # deserialized spans are closed; never re-timed
        return sp

    def render(self, indent: int = 0) -> str:
        """Human-readable indented tree (the Explainer's indentation
        idiom, index/planner.py Explainer)."""
        pad = "  " * indent
        attrs = ""
        if self.attributes:
            short = {
                k: v for k, v in self.attributes.items()
                if not isinstance(v, str) or len(v) <= 64
            }
            if short:
                attrs = " " + json.dumps(short, default=str, sort_keys=True)
        lines = [f"{pad}{self.name} {self.duration_ms:.2f}ms{attrs}"]
        for ev in self.events:
            extra = {k: v for k, v in ev.items() if k not in ("name", "t_ms")}
            tail = f" {json.dumps(extra, default=str)}" if extra else ""
            lines.append(f"{pad}  ! {ev['name']} @{ev['t_ms']:.2f}ms{tail}")
        for c in self.children:
            lines.append(c.render(indent + 1))
        return "\n".join(lines)


class _NoopSpan:
    """Shared do-nothing span/context-manager: what ``span()`` hands out
    when nothing is listening. Every method is a cheap no-op so call
    sites never branch."""

    __slots__ = ()
    recording = False
    name = ""
    trace_id = ""
    span_id = ""
    duration_ms = 0.0
    self_time_ms = 0.0
    attributes: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    children: List[Span] = []

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attr(self, key, value) -> "_NoopSpan":
        return self

    def add_event(self, name, **attrs) -> "_NoopSpan":
        return self

    def walk(self):
        return iter(())

    def find(self, name):
        return []

    def render(self, indent: int = 0) -> str:
        return ""

    def to_dict(self):
        return {}


NOOP = _NoopSpan()

_CURRENT: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "geomesa_tpu_trace_span", default=None
)
_EXPORTERS: List["TraceExporter"] = []
_EXPORTERS_LOCK = threading.Lock()
_log = logging.getLogger("geomesa_tpu.trace")


class _SpanContext:
    """The live edition of ``span()``: enters a new Span as the current
    context, exports the tree from the root's __exit__."""

    __slots__ = ("span", "_token")

    def __init__(self, name: str, parent: Optional[Span],
                 trace_id: Optional[str], attrs: Dict[str, Any]):
        if parent is not None:
            tid = parent.trace_id
            pid = parent.span_id
        else:
            tid = trace_id or _new_id()
            pid = None
        sp = Span(name, tid, pid)
        if attrs:
            sp.attributes.update(attrs)
        if parent is not None:
            parent.children.append(sp)
        self.span = sp
        self._token = None

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self.span
        sp.finish()
        if exc is not None:
            sp.add_event("error", type=type(exc).__name__, message=str(exc))
        if self._token is not None:
            _CURRENT.reset(self._token)
        if sp.parent_id is None and _CURRENT.get() is None:
            _export(sp)
        return False


def span(name: str, trace_id: Optional[str] = None, force: bool = False,
         **attrs: Any):
    """Context manager for one span.

    Activates when a trace is already open (nesting), an exporter is
    installed, or ``force=True`` (the slow-query log needs the tree even
    with no exporter). Otherwise returns the free NOOP singleton — an
    explicit ``trace_id`` (joining a remote trace) only takes effect
    when something is listening."""
    parent = _CURRENT.get()
    if parent is None and not (_EXPORTERS or force):
        return NOOP
    return _SpanContext(name, parent, trace_id, attrs)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    sp = _CURRENT.get()
    return sp.trace_id if sp is not None else None


def event(name: str, **attrs: Any) -> None:
    """Attach a point-in-time event to the current span (no-op outside a
    trace) — how one-shot facts (a fired fault, a degradation) land on
    the query that suffered them."""
    sp = _CURRENT.get()
    if sp is not None:
        sp.add_event(name, **attrs)


def set_attr(key: str, value: Any) -> None:
    sp = _CURRENT.get()
    if sp is not None:
        sp.set_attr(key, value)


def graft(parent: Span, sub: Span, offset_ms: float = 0.0) -> Span:
    """Attach a deserialized remote subtree under ``parent`` — the
    coordinator half of fleet trace stitching (parallel/fleet.py).

    Every span in the subtree is re-keyed onto the parent's trace id
    (the remote side opened its root with the envelope's id, but a
    dropped/foreign id must not fracture the tree) and its wall-clock
    ``start_ms`` is shifted by ``offset_ms`` — the caller computes the
    offset from its OWN clock observations (RPC span start + elapsed)
    plus the remote span's monotonic-derived durations, so a skewed
    remote wall clock can never place the subtree outside the RPC that
    carried it. Span-relative event times need no shift."""
    sub.parent_id = parent.span_id
    for s in sub.walk():
        s.trace_id = parent.trace_id
        s.start_ms += offset_ms
    parent.children.append(sub)
    return sub


def active() -> bool:
    """True when spans would record (exporter installed or trace open)."""
    return bool(_EXPORTERS) or _CURRENT.get() is not None


def wrap(fn: Callable) -> Callable:
    """Bind ``fn`` to the CALLER's context so the active span survives a
    hop onto another thread (executor pools, server handler threads)."""
    ctx = contextvars.copy_context()
    return lambda *a, **k: ctx.run(fn, *a, **k)


# -- exporters ----------------------------------------------------------------


class TraceExporter:
    """Receives each completed ROOT span (children attached)."""

    def export(self, root: Span) -> None:
        raise NotImplementedError


class InMemoryTraceExporter(TraceExporter):
    """Bounded ring of recent trace trees (the InMemoryAuditWriter
    posture) — feeds tests and the /debug/traces endpoint.

    ``root_names`` restricts the ring to trees whose root has one of the
    given names: the debug ring keeps only query trees, so background
    roots (stream polls, ingest block writes) can never evict the traces
    an operator came to read."""

    def __init__(self, capacity: int = 256, root_names=None):
        self.capacity = capacity
        self.root_names = frozenset(root_names) if root_names else None
        self.traces: List[Span] = []
        self._lock = threading.Lock()

    def export(self, root: Span) -> None:
        if self.root_names is not None and root.name not in self.root_names:
            return
        with self._lock:
            self.traces.append(root)
            if len(self.traces) > self.capacity:
                del self.traces[: len(self.traces) - self.capacity]

    def recent(self, n: int = 20) -> List[Span]:
        if n <= 0:  # traces[-0:] would be the WHOLE ring
            return []
        with self._lock:
            return list(self.traces[-n:])


class JsonLinesTraceExporter(TraceExporter):
    """One JSON object per trace tree, appended to a file — offline
    analysis / replay (the DelimitedFileReporter posture)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def export(self, root: Span) -> None:
        line = json.dumps(root.to_dict(), default=str)
        with self._lock:
            with open(self.path, "a") as fh:
                fh.write(line + "\n")


class LoggingTraceExporter(TraceExporter):
    """Rendered trace trees through the logging module."""

    def __init__(self, logger: Optional[logging.Logger] = None):
        self.logger = logger or logging.getLogger("geomesa_tpu.trace")

    def export(self, root: Span) -> None:
        self.logger.info("trace %s\n%s", root.trace_id, root.render())


def install(exporter: TraceExporter) -> TraceExporter:
    with _EXPORTERS_LOCK:
        if exporter not in _EXPORTERS:
            _EXPORTERS.append(exporter)
    return exporter


def uninstall(exporter: TraceExporter) -> None:
    with _EXPORTERS_LOCK:
        try:
            _EXPORTERS.remove(exporter)
        except ValueError:
            pass


class exporting:
    """Scoped install for tests: ``with trace.exporting(ring): ...``"""

    def __init__(self, exporter: TraceExporter):
        self.exporter = exporter

    def __enter__(self) -> TraceExporter:
        return install(self.exporter)

    def __exit__(self, *exc) -> None:
        uninstall(self.exporter)


def _export(root: Span) -> None:
    # telemetry must never take the traced path down with it
    # (the GraphiteReporter drop-the-snapshot posture)
    with _EXPORTERS_LOCK:
        sinks = list(_EXPORTERS)
    for e in sinks:
        try:
            e.export(root)
        except Exception:  # noqa: BLE001 - exporter failure is not query failure
            _log.exception("trace exporter %r failed", type(e).__name__)


_DEBUG_RING: Optional[InMemoryTraceExporter] = None
_DEBUG_RING_REFS = 0
_DEBUG_RING_LOCK = threading.Lock()


def ensure_ring(capacity: int = 256) -> InMemoryTraceExporter:
    """Install (once) the process debug ring behind /debug/traces —
    query trees only, so serving traffic cannot flood the ring with
    poll/ingest roots. Refcounted against ``release_ring()``: each
    server holds one reference, and the last release restores the
    free-when-off no-op path."""
    global _DEBUG_RING, _DEBUG_RING_REFS
    with _DEBUG_RING_LOCK:
        if _DEBUG_RING is None:
            _DEBUG_RING = install(
                InMemoryTraceExporter(
                    capacity,
                    root_names=(
                        # every query-class root (utils/slo.py CLASSES):
                        # exemplar trace ids from any class must resolve
                        # here, and background roots (polls, ingest)
                        # still can never evict them
                        "query", "query.batch", "query.join",
                        "query.aggregate", "query.stream",
                    ),
                )
            )
        _DEBUG_RING_REFS += 1
        return _DEBUG_RING


def release_ring() -> None:
    """Drop one ensure_ring reference; the last one uninstalls the debug
    ring (a short-lived server must not leave the tracer — and up to 256
    retained span trees — active for the rest of the process)."""
    global _DEBUG_RING, _DEBUG_RING_REFS
    with _DEBUG_RING_LOCK:
        if _DEBUG_RING is None:
            return
        _DEBUG_RING_REFS -= 1
        if _DEBUG_RING_REFS > 0:
            return
        ring, _DEBUG_RING, _DEBUG_RING_REFS = _DEBUG_RING, None, 0
    uninstall(ring)


def find_trace(trace_id: str) -> Optional[Span]:
    """Resolve one retained trace tree by id — how the incident report
    (web.py GET /debug/report) turns an exemplar's trace_id into the
    actual span tree. Searches the debug ring (or a test's in-memory
    exporter); None once the ring has rotated past it."""
    if not trace_id:
        return None
    for root in recent_traces(10**9):
        if root.trace_id == trace_id:
            return root
    return None


def blackbox_traces(n: int = 20) -> List[Span]:
    """The crash black box's trace dump (utils/history.py): the last
    ``n`` retained trace trees, [] when no ring is installed. Identical
    to ``recent_traces`` today, but named for its shutdown-path caller —
    the dump must stay a pure read that can run during interpreter
    teardown (no ring installation, no lock beyond the snapshot)."""
    return recent_traces(n)


def recent_traces(n: int = 20) -> List[Span]:
    """Last ``n`` trace trees for /debug/traces: the debug ring when one
    is installed (query-filtered — an application's own unfiltered ring
    must not hijack the endpoint), else the first in-memory exporter
    (a test's ring); [] when none is."""
    with _DEBUG_RING_LOCK:
        ring = _DEBUG_RING
    if ring is not None:
        return ring.recent(n)
    with _EXPORTERS_LOCK:
        sinks = list(_EXPORTERS)
    for e in sinks:
        if isinstance(e, InMemoryTraceExporter):
            return e.recent(n)
    return []
