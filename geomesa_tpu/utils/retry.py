"""Unified retry policy: bounded attempts, decorrelated-jitter backoff.

One implementation of backoff, jitter, deadlines, and retryable-exception
classification for every transient-failure site in the tree — FsDataStore
block I/O, the metadata registry flush, the RemoteLogBroker RPC path, the
stream consumer's poll loop, the blobstore, and the metrics reporters all
route through RetryPolicy (``scripts/lint_robustness.sh`` fails ad-hoc
retry loops). Retries and give-ups are counted in
``utils.audit.robustness_metrics()`` under ``retry.<name>.*`` so chaos
soaks can assert the layer actually absorbed the injected faults.

Backoff is exponential with decorrelated jitter (the AWS architecture
blog's variant): ``sleep_i = min(cap, uniform(base, 3 * sleep_{i-1}))``.
Decorrelation keeps a thundering herd of retriers from re-colliding on
the same schedule; the cap bounds tail latency.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, Union

from geomesa_tpu.utils.audit import robustness_metrics

Retryable = Union[Tuple[Type[BaseException], ...], Callable[[BaseException], bool]]


class RetryPolicy:
    """Retry a callable on transient failures.

    ``retryable`` is an exception-type tuple (default ``(OSError,)`` —
    I/O and connection failures, including injected ones) or a predicate
    ``exc -> bool``. Anything else raises through on the first attempt:
    application errors and deterministic corruption must never be
    hammered. ``deadline_s`` bounds total elapsed time across attempts;
    when it would be exceeded the last error is raised even if attempts
    remain. ``rng``/``sleep`` are injectable for deterministic tests.
    """

    def __init__(
        self,
        name: str = "io",
        max_attempts: int = 4,
        base_s: float = 0.02,
        cap_s: float = 1.0,
        deadline_s: Optional[float] = None,
        retryable: Retryable = (OSError,),
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.name = name
        self.max_attempts = int(max_attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.deadline_s = deadline_s
        self.retryable = retryable
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep

    def is_retryable(self, exc: BaseException) -> bool:
        if isinstance(self.retryable, tuple):
            return isinstance(exc, self.retryable)
        return bool(self.retryable(exc))

    def call(self, fn: Callable, *args, **kwargs):
        """``fn(*args, **kwargs)``, retried on retryable failures. The
        final failure re-raises the ORIGINAL exception — callers keep
        their exception contract.

        Two budgets bound the loop: the policy's own ``deadline_s`` and
        the AMBIENT query deadline (``utils.deadline``) — backoff sleeps
        are clamped to whichever remainder is smaller, so a retry ladder
        can never outlive the query that started it. A sleep that would
        consume the entire remaining budget is skipped: the retry after
        it could only start AT the deadline, so the loop gives up
        immediately instead of burning the budget asleep."""
        from geomesa_tpu.utils import deadline as _deadline

        t0 = time.monotonic()
        ambient = _deadline.ambient()
        prev = self.base_s
        attempt = 1
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                if not self.is_retryable(e):
                    raise
                left = (
                    None
                    if self.deadline_s is None
                    else self.deadline_s - (time.monotonic() - t0)
                )
                if ambient is not None:
                    amb_left = ambient.remaining()
                    left = amb_left if left is None else min(left, amb_left)
                if attempt >= self.max_attempts or (left is not None and left <= 0):
                    robustness_metrics().inc(f"retry.{self.name}.giveup")
                    raise
                prev = min(self.cap_s, self._rng.uniform(self.base_s, prev * 3))
                if left is not None and prev >= left:
                    # the backoff would sleep through the rest of the
                    # budget — the final sleep is pointless; give up NOW
                    # with the budget intact for the caller's cleanup
                    robustness_metrics().inc(f"retry.{self.name}.giveup")
                    raise
                robustness_metrics().inc(f"retry.{self.name}.retries")
                self._sleep(prev)
                attempt += 1

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form of ``call``."""
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return inner
