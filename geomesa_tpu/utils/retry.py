"""Unified retry policy: bounded attempts, decorrelated-jitter backoff.

One implementation of backoff, jitter, deadlines, and retryable-exception
classification for every transient-failure site in the tree — FsDataStore
block I/O, the metadata registry flush, the RemoteLogBroker RPC path, the
stream consumer's poll loop, the blobstore, and the metrics reporters all
route through RetryPolicy (``scripts/lint_robustness.sh`` fails ad-hoc
retry loops). Retries and give-ups are counted in
``utils.audit.robustness_metrics()`` under ``retry.<name>.*`` so chaos
soaks can assert the layer actually absorbed the injected faults.

Backoff is exponential with decorrelated jitter (the AWS architecture
blog's variant): ``sleep_i = min(cap, uniform(base, 3 * sleep_{i-1}))``.
Decorrelation keeps a thundering herd of retriers from re-colliding on
the same schedule; the cap bounds tail latency.

Layered over the per-call ladder is a per-BOUNDARY retry budget (one
token bucket per policy ``name``, shared by every policy instance with
that name): each initial call deposits ``geomesa.retry.budget.ratio``
tokens, the bucket refills at least ``geomesa.retry.budget.min`` tokens
per second, and each retry spends one. The ratio deposit is the classic
~10%-of-traffic rule — under a true outage, retries cannot amplify the
boundary's traffic by more than ~ratio, so a retry storm can't finish
off a struggling dependency. The time-based floor is the Finagle
RetryBudget refinement: low-traffic boundaries (and fault-injection
soaks, whose failure rates dwarf any traffic ratio) still recover the
ability to retry. Exhaustion gives up crisply — the ORIGINAL exception,
plus ``retry.<name>.budget_exhausted`` and a reason-coded decision — so
the failure reads as "budget spent", never as a silent hang.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type, Union

from geomesa_tpu.utils.audit import decision, robustness_metrics

Retryable = Union[Tuple[Type[BaseException], ...], Callable[[BaseException], bool]]

# -- per-boundary retry budgets ----------------------------------------------

# (enabled, deposit ratio, per-second refill floor, bucket cap) — cached
# after first read, the usual free-when-off shape; reset_budgets() for
# tests and config reloads
_CFG: Optional[Tuple[bool, float, float, float]] = None
_BUDGETS: Dict[str, "_TokenBudget"] = {}
_BUDGETS_LOCK = threading.Lock()


def _cfg() -> Tuple[bool, float, float, float]:
    global _CFG
    cfg = _CFG
    if cfg is None:
        from geomesa_tpu.utils.config import (
            RETRY_BUDGET_CAP,
            RETRY_BUDGET_ENABLED,
            RETRY_BUDGET_MIN,
            RETRY_BUDGET_RATIO,
        )

        enabled = RETRY_BUDGET_ENABLED.to_bool()
        ratio = RETRY_BUDGET_RATIO.to_float()
        floor = RETRY_BUDGET_MIN.to_float()
        cap = RETRY_BUDGET_CAP.to_float()
        cfg = (
            True if enabled is None else bool(enabled),
            0.1 if ratio is None else max(0.0, ratio),
            10.0 if floor is None else max(0.0, floor),
            100.0 if cap is None else max(1.0, cap),
        )
        _CFG = cfg
    return cfg


class _TokenBudget:
    """One boundary's bucket. Starts full (a fresh process may retry its
    first failures — cold starts are exactly when dependencies flap)."""

    __slots__ = ("tokens", "cap", "_last", "_lock")

    def __init__(self, cap: float):
        self.cap = cap
        self.tokens = cap
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill_locked(self, floor_per_s: float) -> None:
        now = time.monotonic()
        dt = now - self._last
        self._last = now
        if dt > 0 and floor_per_s > 0:
            self.tokens = min(self.cap, self.tokens + dt * floor_per_s)

    def deposit(self, ratio: float, floor_per_s: float) -> None:
        with self._lock:
            self._refill_locked(floor_per_s)
            self.tokens = min(self.cap, self.tokens + ratio)

    def try_spend(self, floor_per_s: float) -> bool:
        with self._lock:
            self._refill_locked(floor_per_s)
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False


def _budget_for(name: str) -> "_TokenBudget":
    b = _BUDGETS.get(name)
    if b is None:
        with _BUDGETS_LOCK:
            b = _BUDGETS.get(name)
            if b is None:
                b = _TokenBudget(_cfg()[3])
                _BUDGETS[name] = b
    return b


def reset_budgets() -> None:
    """Drop every bucket and the cached knobs (tests, config reloads)."""
    global _CFG
    with _BUDGETS_LOCK:
        _CFG = None
        _BUDGETS.clear()


def budgets_snapshot() -> Dict[str, Dict[str, float]]:
    """Point-in-time token levels per boundary (``/debug/overload``)."""
    with _BUDGETS_LOCK:
        items = list(_BUDGETS.items())
    return {
        name: {"tokens": round(b.tokens, 2), "cap": b.cap}
        for name, b in items
    }


class RetryPolicy:
    """Retry a callable on transient failures.

    ``retryable`` is an exception-type tuple (default ``(OSError,)`` —
    I/O and connection failures, including injected ones) or a predicate
    ``exc -> bool``. Anything else raises through on the first attempt:
    application errors and deterministic corruption must never be
    hammered. ``deadline_s`` bounds total elapsed time across attempts;
    when it would be exceeded the last error is raised even if attempts
    remain. ``rng``/``sleep`` are injectable for deterministic tests.
    """

    def __init__(
        self,
        name: str = "io",
        max_attempts: int = 4,
        base_s: float = 0.02,
        cap_s: float = 1.0,
        deadline_s: Optional[float] = None,
        retryable: Retryable = (OSError,),
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.name = name
        self.max_attempts = int(max_attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.deadline_s = deadline_s
        self.retryable = retryable
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep

    def is_retryable(self, exc: BaseException) -> bool:
        if isinstance(self.retryable, tuple):
            return isinstance(exc, self.retryable)
        return bool(self.retryable(exc))

    def call(self, fn: Callable, *args, **kwargs):
        """``fn(*args, **kwargs)``, retried on retryable failures. The
        final failure re-raises the ORIGINAL exception — callers keep
        their exception contract.

        Two budgets bound the loop: the policy's own ``deadline_s`` and
        the AMBIENT query deadline (``utils.deadline``) — backoff sleeps
        are clamped to whichever remainder is smaller, so a retry ladder
        can never outlive the query that started it. A sleep that would
        consume the entire remaining budget is skipped: the retry after
        it could only start AT the deadline, so the loop gives up
        immediately instead of burning the budget asleep."""
        from geomesa_tpu.utils import deadline as _deadline

        enabled, ratio, floor, _cap = _cfg()
        budget = _budget_for(self.name) if enabled else None
        if budget is not None:
            # the DEPOSIT happens per initial call, not per retry: the
            # bucket tracks the boundary's real traffic, so sustained
            # retries are bounded at ~ratio of it
            budget.deposit(ratio, floor)
        t0 = time.monotonic()
        ambient = _deadline.ambient()
        prev = self.base_s
        attempt = 1
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                if not self.is_retryable(e):
                    raise
                left = (
                    None
                    if self.deadline_s is None
                    else self.deadline_s - (time.monotonic() - t0)
                )
                if ambient is not None:
                    amb_left = ambient.remaining()
                    left = amb_left if left is None else min(left, amb_left)
                if attempt >= self.max_attempts or (left is not None and left <= 0):
                    robustness_metrics().inc(f"retry.{self.name}.giveup")
                    raise
                prev = min(self.cap_s, self._rng.uniform(self.base_s, prev * 3))
                if left is not None and prev >= left:
                    # the backoff would sleep through the rest of the
                    # budget — the final sleep is pointless; give up NOW
                    # with the budget intact for the caller's cleanup
                    robustness_metrics().inc(f"retry.{self.name}.giveup")
                    raise
                if budget is not None and not budget.try_spend(floor):
                    # the boundary-wide budget is spent: more retries
                    # here would amplify whatever is melting the
                    # dependency. Fail crisply with the ORIGINAL error
                    robustness_metrics().inc(
                        f"retry.{self.name}.budget_exhausted"
                    )
                    decision(
                        "retry", "budget_exhausted",
                        policy=self.name, attempt=attempt,
                    )
                    raise
                robustness_metrics().inc(f"retry.{self.name}.retries")
                self._sleep(prev)
                attempt += 1

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form of ``call``."""
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return inner
