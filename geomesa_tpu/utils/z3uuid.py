"""Z3-prefixed feature-id generation.

Reference: geomesa-utils uuid/Z3UuidGenerator (+ Z3FeatureIdGenerator,
geotools/GeoMesaFeatureWriter.scala:43-71): version-4-style UUIDs whose high
bits carry the feature's coarse z3, so ids of spatio-temporally nearby
features share prefixes (id-index locality + shard spreading).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from geomesa_tpu.curve import TimePeriod, time_to_binned
from geomesa_tpu.curve.sfc import Z3SFC


def z3_uuid(x: float, y: float, t_ms: int, period: TimePeriod = TimePeriod.WEEK) -> str:
    """UUID string: [4-bit version=4][20-bit z3 prefix][2-byte bin][random]."""
    bins, offs = time_to_binned(np.asarray([t_ms], dtype=np.int64), period)
    sfc = Z3SFC.for_period(period)
    z = int(sfc.index([x], [y], offs, lenient=True)[0])
    prefix20 = (z >> 43) & 0xFFFFF  # top 20 bits of the 63-bit key
    b = int(bins[0]) & 0xFFFF
    rand = int.from_bytes(os.urandom(8), "big")
    hi = (0x4 << 60) | (prefix20 << 40) | (b << 24) | (rand >> 40) & 0xFFFFFF
    lo = ((0x8 << 60) | (rand & 0x0FFFFFFFFFFFFFFF)) & 0xFFFFFFFFFFFFFFFF
    raw = (hi << 64) | lo
    h = f"{raw:032x}"
    return f"{h[0:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"


def z3_uuid_batch(x, y, t_ms, period: TimePeriod = TimePeriod.WEEK) -> np.ndarray:
    x = np.atleast_1d(np.asarray(x, dtype=np.float64))
    y = np.atleast_1d(np.asarray(y, dtype=np.float64))
    t = np.atleast_1d(np.asarray(t_ms, dtype=np.int64))
    out = np.empty(len(x), dtype=object)
    for i in range(len(x)):
        out[i] = z3_uuid(float(x[i]), float(y[i]), int(t[i]), period)
    return out
