"""SLO engine: declarative per-query-class objectives with multi-window
burn rates over the telemetry timeline.

With GeoBlocks-style caching (PR 8) and coalescing (PR 9) the latency
distribution is strongly bimodal — pyramid hit vs. exact scan, coalesced
vs. solo — so an aggregate p99 actively misleads: it averages two
different machines. This module evaluates objectives PER QUERY CLASS,
the classes derived from the existing ``QueryEvent.outcome`` counters
and root-span timer names the audit layer already writes:

    query               queries / queries.{timeout,shed} / query.scan
    join                queries.join / queries.join.{timeout,shed} / query.join
    aggregate           queries.aggregate / ... / query.aggregate
    stream_first_batch  queries.stream / query.stream.first

(``query_many`` members audit into the ``query`` class — each resolves
under its own root span and budget, PR 4 semantics.)

Two objective kinds per class:

* **availability** — bad = timeout + shed outcomes over the window;
* **latency** — bad = timer samples over the class's threshold
  (``geomesa.slo.<class>.latency.ms``), counted from the timeline's
  per-tick latency-bucket histograms (bucket resolution: a sample in
  the threshold's own power-of-two bucket counts as GOOD — the engine
  under-counts violations by at most one bucket, never cries wolf).

Burn rate = (bad / events) / (1 - objective): 1.0 means the error
budget spends exactly at sustainable pace. A class is VIOLATING when
BOTH the fast window (default 5 m) and the slow window (default 1 h)
burn past their thresholds (defaults 14.4 / 1.0 — the classic
page-on-fast-burn pair) AND the fast window saw at least
``geomesa.slo.min.events`` events. The AND gives fast alert RESET: the
moment the fast window slides clean, /healthz clears, even while the
slow window still remembers the incident.

On a fleet coordinator the same gate ALSO runs per worker, over the
unmerged ``per_worker`` series the timeline rollup keeps
(``timeline.merge_worker_ticks``): one sick worker whose latency the
fleet-merged histogram would dilute below threshold still violates its
class objective, and the verdict is attributed
(``<slo-name>@worker<id>``) so /healthz names both the SLO and the
worker burning it.

Exemplars close the loop: with ``geomesa.slo.exemplars`` on (raised by
the first timeline sampler), every timer keeps (value, trace_id) pairs
per latency bucket (utils/audit.py), so ``GET /debug/slo`` and the
incident report link each class's worst samples straight to retained
traces in ``/debug/traces``.
"""

from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu.utils import audit

# query class -> (events counter, bad-outcome counters, latency timer).
# Derived from what store/datastore.py already writes per class — the
# engine adds no hot-path instrumentation of its own.
CLASSES: Dict[str, Dict[str, Any]] = {
    "query": {
        "counter": "queries",
        "bad": ("queries.timeout", "queries.shed"),
        "timer": "query.scan",
    },
    "join": {
        "counter": "queries.join",
        "bad": ("queries.join.timeout", "queries.join.shed"),
        "timer": "query.join",
    },
    "aggregate": {
        "counter": "queries.aggregate",
        "bad": ("queries.aggregate.timeout", "queries.aggregate.shed"),
        "timer": "query.aggregate",
    },
    "stream_first_batch": {
        "counter": "queries.stream",
        "bad": (),
        "timer": "query.stream.first",
    },
}


@dataclass
class SloSpec:
    """One objective: ``kind`` is ``availability`` (good = outcome ok)
    or ``latency`` (good = under ``latency_ms``); ``objective`` is the
    good-fraction target (0.999 = three nines)."""

    name: str
    cls: str
    kind: str
    objective: float
    latency_ms: Optional[float] = None

    def __post_init__(self):
        if self.cls not in CLASSES:
            raise ValueError(
                f"unknown query class {self.cls!r} (classes: {sorted(CLASSES)})"
            )
        if self.kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if self.kind == "latency" and not self.latency_ms:
            raise ValueError("latency SLOs need latency_ms")


def default_slos() -> List[SloSpec]:
    """The knob-driven default objective set: availability + latency per
    class (availability skipped for the stream class — a stream that
    fails pre-first-byte already audits as a ``query`` outcome)."""
    from geomesa_tpu.utils import config as cfg

    avail = cfg.SLO_AVAILABILITY.to_float() or 0.999
    lat_obj = cfg.SLO_LATENCY_OBJECTIVE.to_float() or 0.99
    lat_ms = {
        "query": cfg.SLO_QUERY_LATENCY_MS.to_float(),
        "join": cfg.SLO_JOIN_LATENCY_MS.to_float(),
        "aggregate": cfg.SLO_AGGREGATE_LATENCY_MS.to_float(),
        "stream_first_batch": cfg.SLO_STREAM_FIRST_LATENCY_MS.to_float(),
    }
    out: List[SloSpec] = []
    for cls in CLASSES:
        if CLASSES[cls]["bad"]:
            out.append(SloSpec(f"{cls}-availability", cls, "availability", avail))
        if lat_ms.get(cls):
            out.append(
                SloSpec(
                    f"{cls}-latency", cls, "latency", lat_obj,
                    latency_ms=float(lat_ms[cls]),
                )
            )
    return out


def slo_knobs() -> tuple:
    """(enabled, fast_s, slow_s, fast_burn, slow_burn, min_events)."""
    from geomesa_tpu.utils import config as cfg

    enabled = bool(cfg.SLO_ENABLED.to_bool())
    fast_s = cfg.SLO_WINDOW_FAST.to_duration_s(300.0)
    slow_s = cfg.SLO_WINDOW_SLOW.to_duration_s(3600.0)
    fast_burn = cfg.SLO_BURN_FAST.to_float() or 14.4
    slow_burn = cfg.SLO_BURN_SLOW.to_float() or 1.0
    me = cfg.SLO_MIN_EVENTS.to_int()
    min_events = 100 if me is None else me
    return enabled, fast_s, slow_s, fast_burn, slow_burn, min_events


class SloEngine:
    """Evaluates a spec set over a ``TimelineSampler``'s ring.

    Pure reads: window sums over recorded snapshots plus exemplar
    lookups — the engine adds nothing to the query path and is safe to
    call from /healthz on every probe."""

    def __init__(self, sampler, specs: Optional[List[SloSpec]] = None):
        self.sampler = sampler
        self.specs = list(specs) if specs is not None else default_slos()

    # -- window folding ------------------------------------------------------

    @staticmethod
    def _fold(snaps: List[Dict[str, Any]], spec: SloSpec) -> Tuple[int, int]:
        """(events, bad) for one spec over one window's snapshots."""
        meta = CLASSES[spec.cls]
        events = 0
        bad = 0
        if spec.kind == "availability":
            for s in snaps:
                deltas = s.get("counters", {})
                events += deltas.get(meta["counter"], 0)
                bad += sum(deltas.get(b, 0) for b in meta["bad"])
            return events, bad
        # latency: fold the per-tick bucket histograms. A sample in the
        # threshold's own bucket reads as good (bucket-edge resolution);
        # buckets strictly above the threshold's are violations.
        thr_bucket = audit.exemplar_bucket(spec.latency_ms / 1000.0)
        for s in snaps:
            t = s.get("timers", {}).get(meta["timer"])
            if not t:
                continue
            events += t.get("count", 0)
            for b, n in t.get("hist", {}).items():
                if int(b) > thr_bucket:
                    bad += n
        return events, bad

    @staticmethod
    def _fold_workers(
        snaps: List[Dict[str, Any]], spec: SloSpec
    ) -> Dict[str, Tuple[int, int]]:
        """Per-worker ``{wid: (events, bad)}`` over one window, folded
        from the fleet rollup's UNMERGED ``per_worker`` series
        (``timeline.merge_worker_ticks``). Empty on non-fleet stores —
        the engine then behaves exactly as before."""
        meta = CLASSES[spec.cls]
        thr_bucket = (
            audit.exemplar_bucket(spec.latency_ms / 1000.0)
            if spec.kind == "latency"
            else None
        )
        acc: Dict[str, List[int]] = {}
        for s in snaps:
            per = ((s.get("fleet") or {}).get("rollup") or {}).get(
                "per_worker"
            ) or {}
            for wid, series in per.items():
                row = acc.setdefault(str(wid), [0, 0])
                if spec.kind == "availability":
                    deltas = series.get("counters") or {}
                    row[0] += int(deltas.get(meta["counter"], 0))
                    row[1] += sum(int(deltas.get(b, 0)) for b in meta["bad"])
                    continue
                t = (series.get("timers") or {}).get(meta["timer"])
                if not t:
                    continue
                row[0] += int(t.get("count", 0))
                for b, n in (t.get("hist") or {}).items():
                    if int(b) > thr_bucket:
                        row[1] += int(n)
        return {w: (e, b) for w, (e, b) in acc.items()}

    @staticmethod
    def _fold_tenants(
        snaps: List[Dict[str, Any]], spec: SloSpec
    ) -> Dict[str, Tuple[int, int]]:
        """Per-tenant ``{label: (events, bad)}`` over one window, folded
        from the sampler's per-tick tenant deltas (utils/tenants.py
        ``timeline_deltas`` — per-class call/bad splits, so a spec folds
        its OWN class). Availability specs only: the tenant rows carry
        no latency histograms, so latency objectives stay store-wide.
        Empty when the tenant meter is off — the engine then behaves
        exactly as before."""
        if spec.kind != "availability":
            return {}
        acc: Dict[str, List[int]] = {}
        for s in snaps:
            for r in s.get("tenants") or []:
                c = (r.get("classes") or {}).get(spec.cls)
                if not c:
                    continue
                row = acc.setdefault(str(r.get("tenant", "")), [0, 0])
                row[0] += int(c.get("calls", 0))
                row[1] += int(c.get("bad", 0))
        return {t: (e, b) for t, (e, b) in acc.items()}

    def _window_eval(
        self, spec: SloSpec, window_s: float, snaps: List[Dict[str, Any]]
    ) -> Dict[str, Any]:
        events, bad = self._fold(snaps, spec)
        budget = 1.0 - spec.objective
        frac = (bad / events) if events else 0.0
        return {
            "window_s": window_s,
            "coverage_s": round(len(snaps) * self.sampler.interval_s, 3),
            "events": events,
            "bad": bad,
            "bad_fraction": round(frac, 6),
            "burn_rate": round(frac / budget, 3) if budget > 0 else 0.0,
        }

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, exemplars: bool = True) -> Dict[str, Any]:
        """The GET /debug/slo body: every spec's fast/slow windows, burn
        rates, violation verdicts, and (unless ``exemplars=False``)
        worst exemplars, trace-linked. The ring is copied ONCE per
        window, not per spec — /healthz probes this on every poll, and
        the evaluation must never contend with the sampler's tick
        beyond two bounded copies."""
        enabled, fast_s, slow_s, fast_burn, slow_burn, min_events = slo_knobs()
        slow_snaps = self.sampler.window(slow_s)
        n_fast = max(1, int(round(fast_s / self.sampler.interval_s)))
        fast_snaps = slow_snaps[-n_fast:] if slow_s >= fast_s else (
            self.sampler.window(fast_s)
        )
        rows = []
        violating = []
        for spec in self.specs:
            fast = self._window_eval(spec, fast_s, fast_snaps)
            slow = self._window_eval(spec, slow_s, slow_snaps)
            violated = (
                enabled
                and fast["events"] >= min_events
                and fast["burn_rate"] >= fast_burn
                and slow["burn_rate"] >= slow_burn
            )
            # per-worker burn (fleet stores only): a single sick worker
            # violates its class objective even when the fleet-merged
            # histogram dilutes it under threshold — skew a sum hides
            workers = self._workers_eval(
                spec,
                fast_snaps,
                slow_snaps,
                (enabled, fast_burn, slow_burn, min_events),
            )
            sick = sorted(w for w, r in workers.items() if r["violating"])
            # per-tenant burn (tenant meter on): one tenant's failing
            # traffic violates ITS objective even while the store-wide
            # series — diluted by every other tenant's successes —
            # stays green (the per-worker skew rule, per label)
            tenants = self._tenants_eval(
                spec,
                fast_snaps,
                slow_snaps,
                (enabled, fast_burn, slow_burn, min_events),
            )
            sick_t = sorted(t for t, r in tenants.items() if r["violating"])
            if violated:
                violating.append(spec.name)
            for w in sick:
                violating.append(f"{spec.name}@worker{w}")
            for t in sick_t:
                violating.append(f"{spec.name}@tenant:{t}")
            rows.append({
                "name": spec.name,
                "class": spec.cls,
                "kind": spec.kind,
                "objective": spec.objective,
                "latency_ms": spec.latency_ms,
                "fast": fast,
                "slow": slow,
                "violating": violated or bool(sick) or bool(sick_t),
                "violating_workers": sick,
                "workers": workers,
                "violating_tenants": sick_t,
                "tenants": tenants,
                "exemplars": (
                    self.worst_exemplars(spec.cls) if exemplars else []
                ),
            })
        return {
            "enabled": enabled,
            "thresholds": {
                "fast_burn": fast_burn,
                "slow_burn": slow_burn,
                "min_events": min_events,
            },
            "slos": rows,
            "violating": violating,
        }

    def _workers_eval(
        self,
        spec: SloSpec,
        fast_snaps: List[Dict[str, Any]],
        slow_snaps: List[Dict[str, Any]],
        knobs: Tuple[bool, float, float, int],
    ) -> Dict[str, Any]:
        """Per-worker burn rows for one spec: ``{wid: {fast, slow,
        violating}}``, workers with zero events omitted. The violation
        rule is the SAME multi-window/min-events gate as the merged
        series, applied to one worker's own events — so the verdict
        names the sick worker instead of waiting for the fleet average
        to cross."""
        enabled, fast_burn, slow_burn, min_events = knobs
        fast_w = self._fold_workers(fast_snaps, spec)
        if not fast_w:
            return {}
        slow_w = self._fold_workers(slow_snaps, spec)
        budget = 1.0 - spec.objective
        out: Dict[str, Any] = {}
        for wid in sorted(set(fast_w) | set(slow_w)):
            fe, fb = fast_w.get(wid, (0, 0))
            se, sb = slow_w.get(wid, (0, 0))
            if not fe and not se:
                continue
            f_rate = (
                round(((fb / fe) if fe else 0.0) / budget, 3)
                if budget > 0
                else 0.0
            )
            s_rate = (
                round(((sb / se) if se else 0.0) / budget, 3)
                if budget > 0
                else 0.0
            )
            out[wid] = {
                "fast": {"events": fe, "bad": fb, "burn_rate": f_rate},
                "slow": {"events": se, "bad": sb, "burn_rate": s_rate},
                "violating": (
                    enabled
                    and fe >= min_events
                    and f_rate >= fast_burn
                    and s_rate >= slow_burn
                ),
            }
        return out

    def _tenants_eval(
        self,
        spec: SloSpec,
        fast_snaps: List[Dict[str, Any]],
        slow_snaps: List[Dict[str, Any]],
        knobs: Tuple[bool, float, float, int],
    ) -> Dict[str, Any]:
        """Per-tenant burn rows for one spec: ``{label: {fast, slow,
        violating}}``, tenants with zero events omitted — the
        ``_workers_eval`` gate (same multi-window/min-events rule)
        applied to one tenant's own events."""
        enabled, fast_burn, slow_burn, min_events = knobs
        fast_t = self._fold_tenants(fast_snaps, spec)
        if not fast_t:
            return {}
        slow_t = self._fold_tenants(slow_snaps, spec)
        budget = 1.0 - spec.objective
        out: Dict[str, Any] = {}
        for label in sorted(set(fast_t) | set(slow_t)):
            fe, fb = fast_t.get(label, (0, 0))
            se, sb = slow_t.get(label, (0, 0))
            if not fe and not se:
                continue
            f_rate = (
                round(((fb / fe) if fe else 0.0) / budget, 3)
                if budget > 0
                else 0.0
            )
            s_rate = (
                round(((sb / se) if se else 0.0) / budget, 3)
                if budget > 0
                else 0.0
            )
            out[label] = {
                "fast": {"events": fe, "bad": fb, "burn_rate": f_rate},
                "slow": {"events": se, "bad": sb, "burn_rate": s_rate},
                "violating": (
                    enabled
                    and fe >= min_events
                    and f_rate >= fast_burn
                    and s_rate >= slow_burn
                ),
            }
        return out

    def violating(self) -> List[str]:
        """Just the violating SLO names — the /healthz degradation
        input: one evaluation with exemplar gathering skipped (nobody
        reads them on a health probe)."""
        return self.evaluate(exemplars=False)["violating"]

    # -- exemplars -----------------------------------------------------------

    def worst_exemplars(self, cls: str, n: int = 3) -> List[Dict[str, Any]]:
        """The class timer's worst retained exemplars (highest occupied
        latency buckets first): ``[{ms, trace_id, date_ms}]`` with ids
        resolvable in /debug/traces while the debug ring retains them.

        On a fleet coordinator, worker-minted exemplars (shipped by the
        ``timeline`` RPC, parallel/fleet.py) merge in with a ``shard``
        annotation: their trace ids are the envelope ids, so with trace
        stitching on they resolve to the SAME stitched trees — and with
        stitching off the shard number still says where the latency was
        paid instead of the sample silently vanishing. A local exemplar
        wins a bucket collision (it resolves without any wire help)."""
        timer = CLASSES[cls]["timer"]
        best: Dict[int, tuple] = {}
        # worker-minted first, so local registries override per bucket
        store = self.sampler._store()
        fleet_fn = getattr(store, "_fleet_exemplars", None)
        if callable(fleet_fn):
            for b, ex in (fleet_fn().get(timer) or {}).items():
                best[int(b)] = ex  # (s, tid, wall_ms, shard)
        for reg in self.sampler.registries:
            slot = reg.exemplars(timer)
            if slot:
                for b, ex in slot["buckets"].items():
                    best[b] = ex  # (s, tid, wall_ms)
        out = []
        for b in sorted(best, reverse=True)[:n]:
            ex = best[b]
            s, tid, wall = ex[0], ex[1], ex[2]
            row = {
                "ms": round(s * 1000.0, 3),
                "trace_id": tid,
                "date_ms": int(wall),
            }
            if len(ex) > 3:
                row["shard"] = int(ex[3])
            out.append(row)
        return out


# -- per-store engines --------------------------------------------------------

_ENGINES: "weakref.WeakKeyDictionary[Any, SloEngine]" = (
    weakref.WeakKeyDictionary()
)
_ENGINES_LOCK = threading.Lock()


def violation_record(engine: SloEngine) -> Optional[Dict[str, Any]]:
    """The durable-spool edition of one SLO evaluation (utils/
    history.py): ``{"violating": [...], "exemplars": {slo: [trace
    ids]}}`` while any class is violating, None while healthy — a
    healthy tick must spool nothing. The exemplar TRACE IDS persist
    (the trees themselves live in the bounded debug ring / black box):
    a postmortem joins them back against whatever ring or blackbox dump
    survived the crash."""
    ev = engine.evaluate(exemplars=True)
    violating = ev.get("violating") or []
    if not violating:
        return None
    exemplars: Dict[str, List[str]] = {}
    for row in ev.get("slos", ()):
        if not row.get("violating"):
            continue
        ids = [
            ex.get("trace_id")
            for ex in row.get("exemplars", ())
            if ex.get("trace_id")
        ]
        if ids:
            exemplars[row["name"]] = ids
    return {"violating": violating, "exemplars": exemplars}


def engine_for(store, create: bool = True) -> Optional[SloEngine]:
    """The store's SLO engine over its timeline sampler (None when the
    engine or the timeline is disabled — /healthz then skips the slo
    block entirely). ``create=False`` builds the (cheap) engine only
    over an ALREADY-RUNNING sampler: a /healthz probe must never be the
    thing that spawns a recorder thread."""
    from geomesa_tpu.utils import timeline

    enabled = slo_knobs()[0]
    if not enabled:
        return None
    with _ENGINES_LOCK:
        got = _ENGINES.get(store)
    if got is not None:
        return got
    sampler = timeline.sampler_for(store, create=create)
    if sampler is None:
        return None
    eng = SloEngine(sampler)
    with _ENGINES_LOCK:
        return _ENGINES.setdefault(store, eng)
