"""Batch analytics layer (the geomesa-spark analog).

Reference: geomesa-spark (SURVEY.md section 2.5) — SpatialRDDProvider feeds
query results into Spark, Spark SQL exposes ~40 ST_* UDFs with Catalyst
pushdown (SQLRules.scala:30-62). Here the same roles are:

  * ``st_functions`` — vectorized ST_* library over columnar arrays
    (numpy on host; the same expressions trace under jax.jit on device).
  * ``SpatialFrame`` — a columnar frame over query results with select /
    where / with_column / group_by aggregation; spatial predicates push
    down to the datastore's CQL planner when constructed via
    ``SpatialFrame.from_query`` (the Catalyst-rule analog).
  * ``SQLContext`` — the SQL string surface: SELECT / WHERE / GROUP BY
    whose ST_* predicates compile into the filter AST and go through the
    cost-based index planner (``SqlResult.explain`` proves the pushdown).
"""

from geomesa_tpu.compute import st_functions as st
from geomesa_tpu.compute.frame import SpatialFrame
from geomesa_tpu.compute.sql import SQLContext, SqlResult
