"""SpatialFrame: columnar analytics over query results.

The Spark-DataFrame role (GeoMesaSparkSQL.scala GeoMesaRelation): construct
from a datastore query — the CQL predicate pushes down to the index planner
exactly as Catalyst rules fold ST_* predicates into relation CQL
(SQLRules.scala:30-62) — then select / where / with_column / group_by
aggregate columnar, on host numpy (device arrays work transparently for
numeric columns under jax).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _non_null(v) -> np.ndarray:
    """Drop NaN (float) / None (object) entries for null-ignoring
    aggregates."""
    v = np.asarray(v)
    if v.dtype.kind == "f":
        return v[~np.isnan(v)]
    if v.dtype.kind == "O":
        return v[np.array([x is not None for x in v], dtype=bool)]
    return v


class SpatialFrame:
    def __init__(self, columns: Dict[str, np.ndarray], ft=None):
        self.columns = dict(columns)
        self.ft = ft

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_query(cls, store, name: str, cql: str = "INCLUDE") -> "SpatialFrame":
        """Predicate pushdown: the CQL goes through the index planner."""
        res = store.query(name, cql)
        return cls(res.columns, res.ft)

    # -- basic ops ----------------------------------------------------------

    def __len__(self):
        for v in self.columns.values():
            return len(v)
        return 0

    @property
    def names(self) -> List[str]:
        return list(self.columns)

    def select(self, *names: str) -> "SpatialFrame":
        keep = set(names) | {"__fid__"}
        cols = {}
        for k, v in self.columns.items():
            base = k.split("__")[0] if "__" in k and not k.startswith("__") else k
            if k in keep or base in keep:
                cols[k] = v
        return SpatialFrame(cols, self.ft)

    def where(self, mask: np.ndarray) -> "SpatialFrame":
        idx = np.flatnonzero(np.asarray(mask))
        return SpatialFrame({k: v[idx] for k, v in self.columns.items()}, self.ft)

    def with_column(self, name: str, values: np.ndarray) -> "SpatialFrame":
        cols = dict(self.columns)
        cols[name] = np.asarray(values)
        return SpatialFrame(cols, self.ft)

    def sort(self, by: str, ascending: bool = True) -> "SpatialFrame":
        order = np.argsort(self.columns[by], kind="stable")
        if not ascending:
            order = order[::-1]
        return SpatialFrame({k: v[order] for k, v in self.columns.items()}, self.ft)

    # -- aggregation --------------------------------------------------------

    _AGGS: Dict[str, Callable] = {
        "count": lambda v: len(v),
        "sum": lambda v: np.sum(v),
        "mean": lambda v: np.mean(v),
        # SQL MIN/MAX ignore NULLs (NaN floats / None objects) — np.min
        # would propagate NaN and TypeError on None; an all-null group
        # yields 0, matching the global-aggregate empty-result shape
        "min": lambda v: (lambda m: np.min(m) if len(m) else 0)(_non_null(v)),
        "max": lambda v: (lambda m: np.max(m) if len(m) else 0)(_non_null(v)),
    }

    def group_by(
        self, key, aggs: Dict[str, Tuple[str, str]]
    ) -> "SpatialFrame":
        """aggs: out_name -> (agg_fn, column); ``key`` is one column name
        or a sequence of them (composite grouping). The
        ShallowJoin/CountByDay analytics shape (geomesa-accumulo-compute)."""
        keys = [key] if isinstance(key, str) else list(key)
        # null group keys (None objects / NaN floats) are SKIPPED, the
        # framework-wide grouping convention (GroupByStat.observe_grouped
        # skips them like the reference skips features whose grouping
        # attribute is missing) — np.unique would otherwise raise
        # comparing None against values
        live = np.ones(len(self), dtype=bool)
        for k in keys:
            col = np.asarray(self.columns[k])
            nulls = self.columns.get(k + "__null")
            if nulls is not None:
                # decoded columns carry nulls as fill values ("" / 0) —
                # the companion mask is the real null signal
                live &= ~np.asarray(nulls, dtype=bool)
            if col.dtype.kind == "O":
                live &= np.array([x is not None for x in col], dtype=bool)
            elif col.dtype.kind == "f":
                live &= ~np.isnan(col)
        frame = self if live.all() else SpatialFrame(
            {k: v[live] for k, v in self.columns.items()}, self.ft
        )
        # factorize each key column, then combine the per-key codes into
        # one group id (mixed dtypes can't stack into a single unique call)
        uniques = []
        codes = None
        for k in keys:
            u, inv = np.unique(frame.columns[k], return_inverse=True)
            uniques.append(u)
            codes = inv if codes is None else codes * len(u) + inv
        if len(keys) == 1:  # already factorized: skip the second unique
            gids = np.arange(len(uniques[0]), dtype=np.int64)
            inverse = codes
        else:
            gids, inverse = np.unique(codes, return_inverse=True)
        out: Dict[str, np.ndarray] = {}
        # decompose each group id back into its per-key unique values
        rem = gids.copy()
        for k, u in zip(reversed(keys), reversed(uniques)):
            out[k] = u[rem % len(u)]
            rem //= len(u)
        out = {k: out[k] for k in keys}  # restore key order
        # sort rows into contiguous group runs ONCE: each aggregate then
        # reads a slice (O(N log N) total, not O(groups x rows) masks)
        order = np.argsort(inverse, kind="stable")
        bounds = np.searchsorted(inverse[order], np.arange(len(gids) + 1))
        for out_name, (fn_name, src) in aggs.items():
            fn = self._AGGS[fn_name]
            src_sorted = frame.columns[src][order]
            out[out_name] = np.asarray(
                [fn(src_sorted[bounds[g]: bounds[g + 1]]) for g in range(len(gids))]
            )
        return SpatialFrame(out, None)

    def to_dict(self) -> Dict[str, list]:
        return {k: v.tolist() for k, v in self.columns.items()}

    # -- spatial join ---------------------------------------------------------

    def spatial_join(
        self,
        other: "SpatialFrame",
        predicate: str = "intersects",
        distance_m: Optional[float] = None,
        suffix: str = "_r",
    ) -> "SpatialFrame":
        """Join this frame's rows against the other frame's geometries
        (the Catalyst spatial-join relation analog, SQLRules.scala spatial
        join folding): point left frames do point-in-geometry; EXTENT left
        frames (no point columns) take an envelope prescreen + exact
        geometry-geometry test per surviving pair ('intersects' =
        geometries_intersect, 'within' = left within right, 'contains' =
        left contains right); 'dwithin' uses a haversine radius against
        the other frame's points (point frames only). Output = matched
        left rows + right columns (suffixed)."""
        gx = self.ft.default_geometry.name if self.ft is not None else "geom"
        left_pts = (gx + "__x") in self.columns
        li: List[int] = []
        ri: List[int] = []
        if predicate in ("intersects", "contains", "within") and not left_pts:
            from geomesa_tpu.geom.predicates import (
                geometries_intersect,
                geometry_within,
            )

            ogx = other.ft.default_geometry.name if other.ft is not None else "geom"
            lg = self.columns[gx]
            env = self._envelopes(gx)
            for j, g in enumerate(other.columns[ogx]):
                if g is None:
                    continue
                qe = g.envelope
                cand = np.flatnonzero(
                    (env[:, 0] <= qe.xmax) & (env[:, 2] >= qe.xmin)
                    & (env[:, 1] <= qe.ymax) & (env[:, 3] >= qe.ymin)
                )
                for i in cand:
                    a = lg[i]
                    if a is None:
                        continue
                    if predicate == "intersects":
                        ok = geometries_intersect(a, g)
                    elif predicate == "within":
                        ok = geometry_within(a, g)
                    else:  # contains: left contains right
                        ok = geometry_within(g, a)
                    if ok:
                        li.append(int(i))
                        ri.append(j)
        elif predicate in ("intersects", "contains", "within"):
            from geomesa_tpu.geom.predicates import points_in_geometry

            lx = self.columns[gx + "__x"]
            ly = self.columns[gx + "__y"]
            geoms = other.columns[
                other.ft.default_geometry.name if other.ft is not None else "geom"
            ]
            for j, g in enumerate(geoms):
                if g is None:
                    continue
                m = points_in_geometry(lx, ly, g)
                hits = np.flatnonzero(m)
                li.extend(hits)
                ri.extend([j] * len(hits))
        elif predicate == "dwithin":
            if distance_m is None:
                raise ValueError("dwithin join needs distance_m")
            if not left_pts:
                raise ValueError("dwithin joins need point geometries")
            from geomesa_tpu.process.geodesy import haversine_m

            lx = self.columns[gx + "__x"]
            ly = self.columns[gx + "__y"]
            ogx = other.ft.default_geometry.name if other.ft is not None else "geom"
            rx = other.columns[ogx + "__x"]
            ry = other.columns[ogx + "__y"]
            for j in range(len(rx)):
                d = haversine_m(lx, ly, rx[j], ry[j])
                hits = np.flatnonzero(d <= distance_m)
                li.extend(hits)
                ri.extend([j] * len(hits))
        else:
            raise ValueError(f"unknown join predicate: {predicate}")
        lidx = np.asarray(li, dtype=np.int64)
        ridx = np.asarray(ri, dtype=np.int64)
        cols = {k: v[lidx] for k, v in self.columns.items()}
        for k, v in other.columns.items():
            cols[(k + suffix) if k in self.columns else k] = v[ridx]
        return SpatialFrame(cols, self.ft)

    def _envelopes(self, gx: str) -> np.ndarray:
        """[n, 4] (xmin, ymin, xmax, ymax) per row — from the companion
        columns when present (what ingest stores for extent schemas), else
        walked from the geometry objects. Null geometries get an inverted
        envelope that never overlaps anything."""
        bx = self.columns.get(gx + "__bxmin")
        if bx is not None:
            return np.stack(
                [
                    np.asarray(bx, dtype=np.float64),
                    np.asarray(self.columns[gx + "__bymin"], dtype=np.float64),
                    np.asarray(self.columns[gx + "__bxmax"], dtype=np.float64),
                    np.asarray(self.columns[gx + "__bymax"], dtype=np.float64),
                ],
                axis=1,
            )
        geoms = self.columns[gx]
        env = np.empty((len(geoms), 4), dtype=np.float64)
        env[:, :2] = np.inf
        env[:, 2:] = -np.inf
        for i, g in enumerate(geoms):
            if g is not None:
                e = g.envelope
                env[i] = (e.xmin, e.ymin, e.xmax, e.ymax)
        return env

    def partition_by_z2(self, bits: int = 8) -> Dict[int, "SpatialFrame"]:
        """Partition rows by low-resolution z2 cell of their point geometry
        (the IndexPartitioner analog): co-locates spatially-near rows so
        downstream per-partition work maps onto mesh shards."""
        from geomesa_tpu.curve import zorder
        from geomesa_tpu.curve.normalized import NormalizedLat, NormalizedLon

        gx = self.ft.default_geometry.name if self.ft is not None else "geom"
        x = self.columns[gx + "__x"]
        y = self.columns[gx + "__y"]
        z = zorder.z2_encode(
            np.asarray(NormalizedLon(bits // 2).normalize(x), dtype=np.int64),
            np.asarray(NormalizedLat(bits // 2).normalize(y), dtype=np.int64),
        )
        out: Dict[int, SpatialFrame] = {}
        for cell in np.unique(z):
            idx = np.flatnonzero(z == cell)
            out[int(cell)] = SpatialFrame(
                {k: v[idx] for k, v in self.columns.items()}, self.ft
            )
        return out
