"""SpatialFrame: columnar analytics over query results.

The Spark-DataFrame role (GeoMesaSparkSQL.scala GeoMesaRelation): construct
from a datastore query — the CQL predicate pushes down to the index planner
exactly as Catalyst rules fold ST_* predicates into relation CQL
(SQLRules.scala:30-62) — then select / where / with_column / group_by
aggregate columnar, on host numpy (device arrays work transparently for
numeric columns under jax).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class SpatialFrame:
    def __init__(self, columns: Dict[str, np.ndarray], ft=None):
        self.columns = dict(columns)
        self.ft = ft

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_query(cls, store, name: str, cql: str = "INCLUDE") -> "SpatialFrame":
        """Predicate pushdown: the CQL goes through the index planner."""
        res = store.query(name, cql)
        return cls(res.columns, res.ft)

    # -- basic ops ----------------------------------------------------------

    def __len__(self):
        for v in self.columns.values():
            return len(v)
        return 0

    @property
    def names(self) -> List[str]:
        return list(self.columns)

    def select(self, *names: str) -> "SpatialFrame":
        keep = set(names) | {"__fid__"}
        cols = {}
        for k, v in self.columns.items():
            base = k.split("__")[0] if "__" in k and not k.startswith("__") else k
            if k in keep or base in keep:
                cols[k] = v
        return SpatialFrame(cols, self.ft)

    def where(self, mask: np.ndarray) -> "SpatialFrame":
        idx = np.flatnonzero(np.asarray(mask))
        return SpatialFrame({k: v[idx] for k, v in self.columns.items()}, self.ft)

    def with_column(self, name: str, values: np.ndarray) -> "SpatialFrame":
        cols = dict(self.columns)
        cols[name] = np.asarray(values)
        return SpatialFrame(cols, self.ft)

    def sort(self, by: str, ascending: bool = True) -> "SpatialFrame":
        order = np.argsort(self.columns[by], kind="stable")
        if not ascending:
            order = order[::-1]
        return SpatialFrame({k: v[order] for k, v in self.columns.items()}, self.ft)

    # -- aggregation --------------------------------------------------------

    _AGGS: Dict[str, Callable] = {
        "count": lambda v: len(v),
        "sum": lambda v: np.sum(v),
        "mean": lambda v: np.mean(v),
        "min": lambda v: np.min(v),
        "max": lambda v: np.max(v),
    }

    def group_by(
        self, key: str, aggs: Dict[str, Tuple[str, str]]
    ) -> "SpatialFrame":
        """aggs: out_name -> (agg_fn, column). The ShallowJoin/CountByDay
        analytics shape (geomesa-accumulo-compute)."""
        col = self.columns[key]
        uniq, inverse = np.unique(col, return_inverse=True)
        out: Dict[str, np.ndarray] = {key: uniq}
        for out_name, (fn_name, src) in aggs.items():
            fn = self._AGGS[fn_name]
            vals = []
            src_col = self.columns[src]
            for g in range(len(uniq)):
                vals.append(fn(src_col[inverse == g]))
            out[out_name] = np.asarray(vals)
        return SpatialFrame(out, None)

    def to_dict(self) -> Dict[str, list]:
        return {k: v.tolist() for k, v in self.columns.items()}
