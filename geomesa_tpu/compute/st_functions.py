"""Vectorized ST_* functions over coordinate arrays.

The geomesa-spark-sql UDF set (SQL*Functions.scala; ~40 functions) re-done
columnar: every function takes/returns numpy arrays (and traces under
jax.jit unchanged for device use). Geometry-typed inputs are (x, y) column
pairs for points; polygons are passed as geometry objects or edge arrays.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from geomesa_tpu.geom.base import Envelope, Geometry, Point, Polygon
from geomesa_tpu.process.geodesy import EARTH_RADIUS_M, haversine_m

# -- constructors ------------------------------------------------------------

def st_point(x, y) -> Tuple[np.ndarray, np.ndarray]:
    return np.asarray(x, dtype=np.float64), np.asarray(y, dtype=np.float64)


def st_make_bbox(xmin, ymin, xmax, ymax) -> Envelope:
    return Envelope(xmin, ymin, xmax, ymax)


def st_geom_from_wkt(wkt: str) -> Geometry:
    from geomesa_tpu.geom.wkt import parse_wkt

    return parse_wkt(wkt)


# -- accessors ---------------------------------------------------------------

def st_x(x, y=None):
    return np.asarray(x, dtype=np.float64)


def st_y(y):
    return np.asarray(y, dtype=np.float64)


def st_envelope(geom: Geometry) -> Envelope:
    return geom.envelope


# -- predicates (vectorized over point columns) ------------------------------

def st_contains(geom: Geometry, x, y) -> np.ndarray:
    """geom contains point(x, y); exact host evaluation."""
    from geomesa_tpu.geom.predicates import points_in_geometry

    return points_in_geometry(np.asarray(x), np.asarray(y), geom)


def st_within(x, y, geom: Geometry) -> np.ndarray:
    return st_contains(geom, x, y)


def st_intersects_bbox(x, y, env: Envelope) -> np.ndarray:
    x = np.asarray(x)
    y = np.asarray(y)
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_dwithin_sphere(x1, y1, x2, y2, meters: float) -> np.ndarray:
    return haversine_m(x1, y1, x2, y2) <= meters


# -- measures ----------------------------------------------------------------

def st_distance_sphere(x1, y1, x2, y2) -> np.ndarray:
    """Great-circle meters (ST_DistanceSphere)."""
    return haversine_m(x1, y1, x2, y2)


def st_distance(x1, y1, x2, y2) -> np.ndarray:
    """Planar degrees distance (ST_Distance)."""
    dx = np.asarray(x2, dtype=np.float64) - np.asarray(x1, dtype=np.float64)
    dy = np.asarray(y2, dtype=np.float64) - np.asarray(y1, dtype=np.float64)
    return np.sqrt(dx * dx + dy * dy)


def st_area(geom: Geometry) -> float:
    """Planar shoelace area for polygons; 0 otherwise."""
    if not isinstance(geom, Polygon):
        return 0.0
    def ring_area(ring):
        c = np.asarray(ring, dtype=np.float64)
        x, y = c[:, 0], c[:, 1]
        return 0.5 * float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))
    area = abs(ring_area(geom.shell))
    for h in getattr(geom, "holes", []) or []:
        area -= abs(ring_area(h))
    return area


def st_length_sphere(xs, ys) -> float:
    """Great-circle length of a line given coordinate arrays (meters)."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    if len(xs) < 2:
        return 0.0
    return float(np.sum(haversine_m(xs[:-1], ys[:-1], xs[1:], ys[1:])))


def st_centroid(xs, ys) -> Tuple[float, float]:
    return float(np.mean(np.asarray(xs, dtype=np.float64))), float(
        np.mean(np.asarray(ys, dtype=np.float64))
    )


# -- transforms --------------------------------------------------------------

def st_translate(x, y, dx: float, dy: float):
    return np.asarray(x, dtype=np.float64) + dx, np.asarray(y, dtype=np.float64) + dy


def st_buffer_bbox(x: float, y: float, meters: float) -> Envelope:
    """Conservative spherical-cap bbox buffer of a point (meters)."""
    from geomesa_tpu.process.geodesy import degrees_box

    return Envelope(*degrees_box(x, y, meters))


def st_geohash(x, y, precision: int = 9) -> np.ndarray:
    from geomesa_tpu.utils.geohash import encode

    return encode(x, y, precision)


def st_convex_hull(xs, ys) -> "Geometry":
    """Convex hull of a point set (Andrew's monotone chain) — the
    ConvexHull UDAF analog (geomesa-spark-sql SQLSpatialAccumulatorFunction).
    Returns a Polygon (or Point/LineString for degenerate inputs)."""
    from geomesa_tpu.geom.base import LineString, Point, Polygon

    pts = np.unique(
        np.stack([np.asarray(xs, float), np.asarray(ys, float)], axis=1), axis=0
    )
    if len(pts) == 1:
        return Point(pts[0, 0], pts[0, 1])
    if len(pts) == 2:
        return LineString(pts)

    def cross2(o, a, b) -> float:
        return (a[0] - o[0]) * (b[1] - o[1]) - (a[1] - o[1]) * (b[0] - o[0])

    def half(points):
        out: list = []
        for p in points:
            while len(out) >= 2 and cross2(out[-2], out[-1], p) <= 0:
                out.pop()
            out.append(p)
        return out

    lower = half(pts)
    upper = half(pts[::-1])
    hull = np.asarray(lower[:-1] + upper[:-1])
    if len(hull) < 3:
        return LineString(pts)
    return Polygon(np.vstack([hull, hull[:1]]))


def st_bin_time(t_ms, period="week"):
    """(bin, offset) pair columns (the z3 binned-time transform)."""
    from geomesa_tpu.curve import time_to_binned

    return time_to_binned(np.asarray(t_ms, dtype=np.int64), period)
