"""Vectorized ST_* functions over coordinate arrays.

The geomesa-spark-sql UDF set (SQL*Functions.scala; ~40 functions) re-done
columnar: every function takes/returns numpy arrays (and traces under
jax.jit unchanged for device use). Geometry-typed inputs are (x, y) column
pairs for points; polygons are passed as geometry objects or edge arrays.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from geomesa_tpu.geom.base import Envelope, Geometry, Point, Polygon
from geomesa_tpu.process.geodesy import EARTH_RADIUS_M, haversine_m

# -- constructors ------------------------------------------------------------

def st_point(x, y) -> Tuple[np.ndarray, np.ndarray]:
    return np.asarray(x, dtype=np.float64), np.asarray(y, dtype=np.float64)


def st_make_bbox(xmin, ymin, xmax, ymax) -> Envelope:
    return Envelope(xmin, ymin, xmax, ymax)


def st_geom_from_wkt(wkt: str) -> Geometry:
    from geomesa_tpu.geom.wkt import parse_wkt

    return parse_wkt(wkt)


# -- accessors ---------------------------------------------------------------

def st_x(x, y=None):
    return np.asarray(x, dtype=np.float64)


def st_y(y):
    return np.asarray(y, dtype=np.float64)


def st_envelope(geom: Geometry) -> Envelope:
    return geom.envelope


# -- predicates (vectorized over point columns) ------------------------------

def st_contains(geom: Geometry, x, y) -> np.ndarray:
    """geom contains point(x, y); exact host evaluation."""
    from geomesa_tpu.geom.predicates import points_in_geometry

    return points_in_geometry(np.asarray(x), np.asarray(y), geom)


def st_within(x, y, geom: Geometry) -> np.ndarray:
    return st_contains(geom, x, y)


def st_intersects_bbox(x, y, env: Envelope) -> np.ndarray:
    x = np.asarray(x)
    y = np.asarray(y)
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def st_dwithin_sphere(x1, y1, x2, y2, meters: float) -> np.ndarray:
    return haversine_m(x1, y1, x2, y2) <= meters


# -- measures ----------------------------------------------------------------

def st_distance_sphere(x1, y1, x2, y2) -> np.ndarray:
    """Great-circle meters (ST_DistanceSphere)."""
    return haversine_m(x1, y1, x2, y2)


def st_distance(x1, y1, x2, y2) -> np.ndarray:
    """Planar degrees distance (ST_Distance)."""
    dx = np.asarray(x2, dtype=np.float64) - np.asarray(x1, dtype=np.float64)
    dy = np.asarray(y2, dtype=np.float64) - np.asarray(y1, dtype=np.float64)
    return np.sqrt(dx * dx + dy * dy)


def st_area(geom: Geometry) -> float:
    """Planar shoelace area for polygons; 0 otherwise."""
    if not isinstance(geom, Polygon):
        return 0.0
    def ring_area(ring):
        c = np.asarray(ring, dtype=np.float64)
        x, y = c[:, 0], c[:, 1]
        return 0.5 * float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))
    area = abs(ring_area(geom.shell))
    for h in getattr(geom, "holes", []) or []:
        area -= abs(ring_area(h))
    return area


def st_length_sphere(xs, ys) -> float:
    """Great-circle length of a line given coordinate arrays (meters)."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    if len(xs) < 2:
        return 0.0
    return float(np.sum(haversine_m(xs[:-1], ys[:-1], xs[1:], ys[1:])))


def st_centroid(xs, ys) -> Tuple[float, float]:
    return float(np.mean(np.asarray(xs, dtype=np.float64))), float(
        np.mean(np.asarray(ys, dtype=np.float64))
    )


# -- transforms --------------------------------------------------------------

def st_translate(x, y, dx: float, dy: float):
    return np.asarray(x, dtype=np.float64) + dx, np.asarray(y, dtype=np.float64) + dy


def st_buffer_bbox(x: float, y: float, meters: float) -> Envelope:
    """Conservative spherical-cap bbox buffer of a point (meters)."""
    from geomesa_tpu.process.geodesy import degrees_box

    return Envelope(*degrees_box(x, y, meters))


def st_geohash(x, y, precision: int = 9) -> np.ndarray:
    from geomesa_tpu.utils.geohash import encode

    return encode(x, y, precision)


def st_convex_hull(xs, ys) -> "Geometry":
    """Convex hull of a point set (Andrew's monotone chain) — the
    ConvexHull UDAF analog (geomesa-spark-sql SQLSpatialAccumulatorFunction).
    Returns a Polygon (or Point/LineString for degenerate inputs)."""
    from geomesa_tpu.geom.base import LineString, Point, Polygon

    pts = np.unique(
        np.stack([np.asarray(xs, float), np.asarray(ys, float)], axis=1), axis=0
    )
    if len(pts) == 1:
        return Point(pts[0, 0], pts[0, 1])
    if len(pts) == 2:
        return LineString(pts)

    def cross2(o, a, b) -> float:
        return (a[0] - o[0]) * (b[1] - o[1]) - (a[1] - o[1]) * (b[0] - o[0])

    def half(points):
        out: list = []
        for p in points:
            while len(out) >= 2 and cross2(out[-2], out[-1], p) <= 0:
                out.pop()
            out.append(p)
        return out

    lower = half(pts)
    upper = half(pts[::-1])
    hull = np.asarray(lower[:-1] + upper[:-1])
    if len(hull) < 3:
        return LineString(pts)
    return Polygon(np.vstack([hull, hull[:1]]))


def st_bin_time(t_ms, period="week"):
    """(bin, offset) pair columns (the z3 binned-time transform)."""
    from geomesa_tpu.curve import time_to_binned

    return time_to_binned(np.asarray(t_ms, dtype=np.int64), period)


# -- constructors (text / geohash / parts) -----------------------------------

def st_make_point(x: float, y: float) -> Point:
    return Point(float(x), float(y))


def st_make_line(points) -> "Geometry":
    """points: [(x, y), ...] or Nx2 array -> LineString."""
    from geomesa_tpu.geom.base import LineString

    return LineString(np.asarray(points, dtype=np.float64))


def st_make_polygon(shell) -> Polygon:
    """shell: closed ring [(x, y), ...] -> Polygon."""
    return Polygon(np.asarray(shell, dtype=np.float64))


def st_geom_from_text(wkt: str) -> Geometry:
    return st_geom_from_wkt(wkt)


def st_point_from_text(wkt: str) -> Point:
    g = st_geom_from_wkt(wkt)
    if not isinstance(g, Point):
        raise ValueError("ST_PointFromText needs POINT wkt")
    return g


def st_line_from_text(wkt: str) -> "Geometry":
    from geomesa_tpu.geom.base import LineString

    g = st_geom_from_wkt(wkt)
    if not isinstance(g, LineString):
        raise ValueError("ST_LineFromText needs LINESTRING wkt")
    return g


def st_polygon_from_text(wkt: str) -> Polygon:
    g = st_geom_from_wkt(wkt)
    if not isinstance(g, Polygon):
        raise ValueError("ST_PolygonFromText needs POLYGON wkt")
    return g


def st_geom_from_geohash(gh: str) -> Polygon:
    """Geohash cell -> its bounding polygon (ST_GeomFromGeoHash)."""
    from geomesa_tpu.utils.geohash import decode_bounds

    xmin, ymin, xmax, ymax = decode_bounds(gh)
    return Envelope(xmin, ymin, xmax, ymax).to_polygon()


def st_box2d_from_geohash(gh: str) -> Envelope:
    from geomesa_tpu.utils.geohash import decode_bounds

    return Envelope(*decode_bounds(gh))


# -- accessors / converters ---------------------------------------------------

def st_as_text(g: Geometry) -> str:
    from geomesa_tpu.geom.wkt import to_wkt

    return to_wkt(g)


def st_as_geojson(g: Geometry) -> str:
    import json

    from geomesa_tpu.geom.base import LineString

    if isinstance(g, Point):
        return json.dumps({"type": "Point", "coordinates": [g.x, g.y]})
    if isinstance(g, LineString):
        return json.dumps(
            {"type": "LineString", "coordinates": np.asarray(g.coords).tolist()}
        )
    if isinstance(g, Polygon):
        rings = [np.asarray(r).tolist() for r in [g.shell, *g.holes]]
        return json.dumps({"type": "Polygon", "coordinates": rings})
    raise ValueError(f"Cannot serialize {type(g).__name__}")


def st_num_points(g: Geometry) -> int:
    from geomesa_tpu.geom.base import LineString, _Multi

    if isinstance(g, Point):
        return 1
    if isinstance(g, LineString):
        return len(np.asarray(g.coords))
    if isinstance(g, Polygon):
        return sum(len(np.asarray(r)) for r in [g.shell, *g.holes])
    if isinstance(g, _Multi):
        return sum(st_num_points(m) for m in g.geoms)
    raise ValueError(f"ST_NumPoints: unsupported {type(g).__name__}")


def st_is_empty(g) -> bool:
    return g is None or st_num_points(g) == 0


def st_is_valid(g) -> bool:
    """Light validity: non-empty, finite coordinates, closed polygon rings."""
    if g is None:
        return False
    if isinstance(g, Point):
        return bool(np.isfinite([g.x, g.y]).all())
    from geomesa_tpu.geom.base import LineString

    if isinstance(g, LineString):
        c = np.asarray(g.coords)
        return len(c) >= 2 and bool(np.isfinite(c).all())
    if isinstance(g, Polygon):
        for r in [g.shell, *g.holes]:
            c = np.asarray(r)
            if len(c) < 4 or not np.isfinite(c).all() or not np.allclose(c[0], c[-1]):
                return False
        return True
    from geomesa_tpu.geom.base import _Multi

    if isinstance(g, _Multi):
        return len(g.geoms) > 0 and all(st_is_valid(m) for m in g.geoms)
    return False


def st_exterior_ring(g: Polygon) -> "Geometry":
    from geomesa_tpu.geom.base import LineString

    return LineString(np.asarray(g.shell))


def st_coord_dim(g: Geometry) -> int:
    return 2


# GeoMesa-parity alias for the existing accessor (SQLSpatialAccessors)
st_bounding_box = st_envelope


def st_expand_bbox(env: Envelope, dx: float, dy: float = None) -> Envelope:
    dy = dx if dy is None else dy
    return Envelope(env.xmin - dx, env.ymin - dy, env.xmax + dx, env.ymax + dy)


# -- row-wise predicates over geometry object columns -------------------------

def st_intersects_geoms(geoms, query: Geometry) -> np.ndarray:
    """Vectorized-over-rows exact intersects for object geometry columns."""
    from geomesa_tpu.geom.predicates import geometries_intersect

    return np.fromiter(
        (g is not None and geometries_intersect(g, query) for g in geoms),
        bool,
        len(geoms),
    )


def st_within_geoms(geoms, query: Geometry) -> np.ndarray:
    from geomesa_tpu.geom.predicates import geometry_within

    return np.fromiter(
        (g is not None and geometry_within(g, query) for g in geoms),
        bool,
        len(geoms),
    )


def st_disjoint_geoms(geoms, query: Geometry) -> np.ndarray:
    out = st_intersects_geoms(geoms, query)
    notnull = np.fromiter((g is not None for g in geoms), bool, len(geoms))
    return ~out & notnull
