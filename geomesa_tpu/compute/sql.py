"""SQL string surface over the datastore: SELECT with ST_* pushdown.

The Catalyst-rule analog (geomesa-spark-sql .../SQLRules.scala:30-62 folds
``ScalaUDF(ST_*)`` predicates in the WHERE clause into the relation's CQL
so the z-index answers them; SQLTypes registers the ~40 ST_* UDFs): a
small SELECT / FROM / WHERE / GROUP BY / ORDER BY / LIMIT dialect whose
spatial and attribute predicates compile DIRECTLY to the filter AST and
go through the cost-based planner — ``SqlResult.explain`` shows the index
the pushdown chose. Aggregations (count/sum/avg/min/max, grouped or
global) and scalar ST_* projections run client-side over the columnar
result, like the reference's Spark stage after the pushed scan.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.compute import st_functions as st
from geomesa_tpu.compute.frame import SpatialFrame
from geomesa_tpu.filter import ast
from geomesa_tpu.geom.base import Envelope, Geometry, Point, Polygon
from geomesa_tpu.geom.wkt import parse_wkt
from geomesa_tpu.index.planner import Query

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<str>'(?:[^']|'')*')
      | (?P<num>-?\d+\.?\d*(?:[eE][+-]?\d+)?)
      | (?P<ident>[A-Za-z_][A-Za-z_0-9]*(?:\.[A-Za-z_][A-Za-z_0-9]*)?)
      | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*)
    )""",
    re.VERBOSE,
)

_AGG_FNS = {"count", "sum", "avg", "mean", "min", "max"}


class SqlError(ValueError):
    pass


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip():
                raise SqlError(f"Cannot tokenize at: {text[pos:pos+25]!r}")
            break
        pos = m.end()
        for kind in ("str", "num", "ident", "op"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("end", ""))
    return out


class _Parser:
    def __init__(self, text: str):
        self.toks = _tokenize(text)
        self.i = 0

    # -- token helpers -------------------------------------------------------

    def peek(self, kw: Optional[str] = None):
        kind, v = self.toks[self.i]
        if kw is not None:
            return kind == "ident" and v.upper() == kw
        return kind, v

    def take(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_kw(self, kw: str):
        kind, v = self.take()
        if kind != "ident" or v.upper() != kw:
            raise SqlError(f"Expected {kw}, got {v!r}")

    def expect_op(self, op: str):
        kind, v = self.take()
        if kind != "op" or v != op:
            raise SqlError(f"Expected {op!r}, got {v!r}")

    def accept_kw(self, kw: str) -> bool:
        if self.peek(kw):
            self.i += 1
            return True
        return False

    # -- grammar -------------------------------------------------------------

    def parse(self) -> dict:
        self.expect_kw("SELECT")
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        self.expect_kw("FROM")
        kind, table = self.take()
        if kind != "ident":
            raise SqlError("Expected table name after FROM")
        alias = None
        if self.peek()[0] == "ident" and not self.peek("WHERE") and not any(
            self.peek(k) for k in ("JOIN", "GROUP", "ORDER", "LIMIT", "HAVING")
        ):
            alias = self.ident()
        join = None
        if self.accept_kw("JOIN"):
            kind, rtable = self.take()
            if kind != "ident":
                raise SqlError("Expected table name after JOIN")
            ralias = None
            if self.peek()[0] == "ident" and not self.peek("ON"):
                ralias = self.ident()
            self.expect_kw("ON")
            kind, fn = self.take()
            if kind != "ident" or not fn.lower().startswith("st_"):
                raise SqlError("JOIN ON needs an ST_* predicate")
            self.expect_op("(")
            on_args = self.call_args()
            join = {
                "table": rtable,
                "alias": ralias or rtable,
                "fn": fn.lower(),
                "args": on_args,
            }
        where = None
        if self.accept_kw("WHERE"):
            where = self.or_expr()
        group = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group.append(self.ident())
            while self.accept_op(","):
                group.append(self.ident())
        having = None
        if self.accept_kw("HAVING"):
            having = self.having_expr()
        order = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                col = self.ident()
                asc = True
                if self.accept_kw("DESC"):
                    asc = False
                else:
                    self.accept_kw("ASC")
                order.append((col, asc))
                if not self.accept_op(","):
                    break
        limit = None
        if self.accept_kw("LIMIT"):
            kind, v = self.take()
            if kind != "num":
                raise SqlError("Expected number after LIMIT")
            limit = int(float(v))
        kind, v = self.take()
        if kind != "end":
            raise SqlError(f"Trailing input at {v!r}")
        return {
            "items": items,
            "table": table,
            "alias": alias or table,
            "join": join,
            "where": where,
            "group": group,
            "having": having,
            "order": order,
            "limit": limit,
        }

    # HAVING: boolean combinations of comparisons whose left side is an
    # aggregate call or an aggregate's (output) alias, right side a literal
    def having_expr(self):
        node = self._having_and()
        while self.accept_kw("OR"):
            node = ("or", node, self._having_and())
        return node

    def _having_and(self):
        node = self._having_not()
        while self.accept_kw("AND"):
            node = ("and", node, self._having_not())
        return node

    def _having_not(self):
        if self.accept_kw("NOT"):
            return ("not", self._having_not())
        if self.accept_op("("):
            node = self.having_expr()
            self.expect_op(")")
            return node
        return self._having_cmp()

    def _having_cmp(self):
        kind, v = self.take()
        if kind != "ident":
            raise SqlError(f"Bad HAVING expression at {v!r}")
        nk, nv = self.toks[self.i]
        if nk == "op" and nv == "(":
            low = v.lower()
            if low not in _AGG_FNS:
                raise SqlError(f"HAVING supports aggregate calls, got {v}")
            self.i += 1
            arg = "*" if self.accept_op("*") else self.ident()
            self.expect_op(")")
            lhs = ("agg", low, arg)
        else:
            lhs = ("name", v)
        kind, op = self.take()
        if kind != "op" or op not in ("=", "!=", "<>", "<", "<=", ">", ">="):
            raise SqlError(f"Expected comparison in HAVING, got {op!r}")
        kind, rv = self.take()
        if kind == "num":
            val = float(rv)
        elif kind == "str":
            val = rv[1:-1].replace("''", "'")
        else:
            raise SqlError(f"Expected literal in HAVING, got {rv!r}")
        return ("cmp", lhs, op, val)

    def accept_op(self, op: str) -> bool:
        kind, v = self.toks[self.i]
        if kind == "op" and v == op:
            self.i += 1
            return True
        return False

    def ident(self) -> str:
        kind, v = self.take()
        if kind != "ident":
            raise SqlError(f"Expected identifier, got {v!r}")
        return v

    def select_item(self) -> dict:
        kind, v = self.toks[self.i]
        if kind == "op" and v == "*":
            self.i += 1
            return {"kind": "star"}
        if kind == "ident":
            name = v
            nk, nv = self.toks[self.i + 1]
            if nk == "op" and nv == "(":
                self.i += 2
                low = name.lower()
                if low in _AGG_FNS:
                    if self.accept_op("*"):
                        arg = "*"
                    else:
                        arg = self.ident()
                    self.expect_op(")")
                    item = {"kind": "agg", "fn": low, "arg": arg,
                            "alias": _agg_alias(low, arg)}
                elif low.startswith("st_"):
                    args = self.call_args()
                    item = {"kind": "stfn", "fn": low, "args": args,
                            "alias": low}
                else:
                    raise SqlError(f"Unknown function {name}")
                if self.accept_kw("AS"):
                    item["alias"] = self.ident()
                return item
            self.i += 1
            item = {"kind": "col", "name": name, "alias": name}
            if self.accept_kw("AS"):
                item["alias"] = self.ident()
            return item
        raise SqlError(f"Bad select item at {v!r}")

    def call_args(self) -> list:
        """Arguments of an already-opened call; consumes the ')'."""
        args = []
        if not self.accept_op(")"):
            args.append(self.value_expr())
            while self.accept_op(","):
                args.append(self.value_expr())
            self.expect_op(")")
        return args

    def value_expr(self):
        """Literal, column reference, or ST_* constructor call."""
        kind, v = self.take()
        if kind == "str":
            return ("lit", v[1:-1].replace("''", "'"))
        if kind == "num":
            return ("lit", float(v) if "." in v or "e" in v.lower() else int(v))
        if kind == "ident":
            nk, nv = self.toks[self.i]
            if nk == "op" and nv == "(":
                self.i += 1
                fn = v.lower()
                args = self.call_args()
                return ("call", fn, args)
            return ("col", v)
        raise SqlError(f"Bad value at {v!r}")

    # WHERE expression with OR < AND < NOT precedence
    def or_expr(self) -> ast.Filter:
        left = self.and_expr()
        while self.accept_kw("OR"):
            left = ast.Or([left, self.and_expr()])
        return left

    def and_expr(self) -> ast.Filter:
        left = self.not_expr()
        while self.accept_kw("AND"):
            left = ast.And([left, self.not_expr()])
        return left

    def not_expr(self) -> ast.Filter:
        if self.accept_kw("NOT"):
            return ast.Not(self.not_expr())
        if self.accept_op("("):
            f = self.or_expr()
            self.expect_op(")")
            return f
        return self.predicate()

    def predicate(self) -> ast.Filter:
        kind, v = self.toks[self.i]
        if kind != "ident":
            raise SqlError(f"Expected predicate at {v!r}")
        low = v.lower()
        if low.startswith("st_") or low == "bbox":
            self.i += 1
            self.expect_op("(")
            args = self.call_args()
            return self.spatial_predicate(low, args)
        prop = self.ident()
        if self.accept_kw("BETWEEN"):
            lo = self.value_expr()
            self.expect_kw("AND")
            hi = self.value_expr()
            return ast.Between(prop, _lit(lo), _lit(hi))
        if self.accept_kw("LIKE"):
            kind, pat = self.take()
            if kind != "str":
                raise SqlError("LIKE needs a string pattern")
            return ast.Like(prop, pat[1:-1].replace("''", "'"))
        if self.accept_kw("IN"):
            self.expect_op("(")
            vals = [_lit(self.value_expr())]
            while self.accept_op(","):
                vals.append(_lit(self.value_expr()))
            self.expect_op(")")
            return ast.InList(prop, vals)
        if self.accept_kw("IS"):
            negate = self.accept_kw("NOT")
            self.expect_kw("NULL")
            return ast.IsNull(prop, negate=negate)
        kind, op = self.take()
        if kind != "op" or op not in ("=", "<>", "!=", "<", "<=", ">", ">="):
            raise SqlError(f"Bad comparison operator {op!r}")
        rhs = _lit(self.value_expr())
        if op == "!=":
            op = "<>"
        return ast.Cmp(prop, op, rhs)

    # -- ST_* predicate folding (SQLRules.scala:33-62 analog) -----------------

    def spatial_predicate(self, fn: str, args: list) -> ast.Filter:
        if fn == "bbox":
            col = _column_name(args[0])
            vals = [float(_lit(a)) for a in args[1:5]]
            return ast.BBox(col, *vals)
        if fn == "st_dwithin":
            col, geom, swapped = _col_and_geom(args[0], args[1])
            dist = float(_lit(args[2]))
            unit = "meters"
            if len(args) > 3:
                unit = str(_lit(args[3]))
            return ast.DWithin(col, geom, dist, unit)
        if fn not in (
            "st_contains", "st_within", "st_intersects", "st_disjoint",
            "st_equals",
        ):
            raise SqlError(f"Unsupported spatial predicate {fn}")
        col, geom, swapped = _col_and_geom(args[0], args[1])
        if fn == "st_intersects":
            return ast.Intersects(col, geom)
        if fn == "st_disjoint":
            return ast.Disjoint(col, geom)
        if fn == "st_equals":
            return ast.And([ast.Within(col, geom), ast.Contains(col, geom)])
        # contains/within: direction depends on which argument is the column
        if fn == "st_contains":
            # contains(a, b): b inside a
            return ast.Within(col, geom) if swapped else ast.Contains(col, geom)
        # within(a, b): a inside b
        return ast.Contains(col, geom) if swapped else ast.Within(col, geom)


def _lit(v):
    if v[0] != "lit":
        raise SqlError(f"Expected literal, got {v!r}")
    return v[1]


def _agg_alias(fn: str, arg: str) -> str:
    return f"{fn}_{arg if arg != '*' else 'all'}"


def _having_agg_terms(node, out: list) -> None:
    """Collect every ('agg', fn, arg) left side in a HAVING tree."""
    k = node[0]
    if k in ("or", "and"):
        _having_agg_terms(node[1], out)
        _having_agg_terms(node[2], out)
    elif k == "not":
        _having_agg_terms(node[1], out)
    elif k == "cmp" and node[1][0] == "agg":
        out.append(node[1])


def _having_mask(
    node, columns: Dict[str, np.ndarray], aggmap: Optional[dict] = None
) -> np.ndarray:
    k = node[0]
    if k == "or":
        return _having_mask(node[1], columns, aggmap) | _having_mask(
            node[2], columns, aggmap
        )
    if k == "and":
        return _having_mask(node[1], columns, aggmap) & _having_mask(
            node[2], columns, aggmap
        )
    if k == "not":
        return ~_having_mask(node[1], columns, aggmap)
    _, lhs, op, val = node
    if lhs[0] == "agg":
        name = (aggmap or {}).get((lhs[1], lhs[2]), _agg_alias(lhs[1], lhs[2]))
    else:
        name = lhs[1]
    col = columns.get(name)
    if col is None:
        raise SqlError(f"HAVING references unknown column {name}")
    if op in ("!=", "<>"):
        return col != val
    return {
        "=": lambda c: c == val,
        "<": lambda c: c < val,
        "<=": lambda c: c <= val,
        ">": lambda c: c > val,
        ">=": lambda c: c >= val,
    }[op](col)


def _resolve_having(node, resolve, renames=None):
    """Qualify a JOIN query's HAVING tree. Aggregate args resolve through
    the alias map (unqualified real columns are rejected, same as SELECT
    aggs); qualified NAME references resolve to the post-rename output
    column so ambiguous bare group keys (a.name + b.name) bind to the
    right relation's column, never silently to the left's."""
    renames = renames or {}
    k = node[0]
    if k in ("or", "and"):
        return (
            k,
            _resolve_having(node[1], resolve, renames),
            _resolve_having(node[2], resolve, renames),
        )
    if k == "not":
        return (k, _resolve_having(node[1], resolve, renames))
    _, lhs, op, val = node
    if lhs[0] == "agg":
        if "." in lhs[2]:
            lhs = ("agg", lhs[1], resolve(lhs[2]))
        elif lhs[2] != "*":
            raise SqlError(
                f"JOIN columns must be qualified: {lhs[2]} (in HAVING)"
            )
    elif lhs[0] == "name" and "." in lhs[1]:
        src = resolve(lhs[1])
        lhs = ("name", renames.get(src, src))
    return ("cmp", lhs, op, val)


def _with_having_aggs(having, aggs):
    """(aggs + hidden HAVING-only aggregates, hidden aliases, aggmap).

    Dedupes by (fn, arg) so a HAVING aggregate that matches a SELECTed one
    (even under a user alias) reuses its column instead of computing the
    same aggregate twice; aggmap maps (fn, arg) -> output column name for
    the mask evaluation."""
    if having is None:
        return aggs, [], {}
    terms: list = []
    _having_agg_terms(having, terms)
    aggmap = {(it["fn"], it["arg"]): it["alias"] for it in aggs}
    taken = {it["alias"] for it in aggs}
    hidden = []
    out = list(aggs)
    for _tag, fn, arg in terms:
        if (fn, arg) in aggmap:
            continue
        alias = _agg_alias(fn, arg)
        if alias in taken:  # user AS-alias collides; find a free name
            i = 2
            while f"{alias}_{i}" in taken:
                i += 1
            alias = f"{alias}_{i}"
        out.append({"kind": "agg", "fn": fn, "arg": arg, "alias": alias})
        hidden.append(alias)
        taken.add(alias)
        aggmap[(fn, arg)] = alias
    return out, hidden, aggmap


def _apply_having(out, having, hidden, aggmap):
    """Filter aggregated rows by the HAVING mask; drop hidden columns."""
    m = _having_mask(having, out.columns, aggmap)
    return SpatialFrame(
        {k: v[m] for k, v in out.columns.items() if k not in hidden},
        out.ft,
    )


def _project_plain(columns: Dict[str, np.ndarray], plain_items) -> Dict[str, np.ndarray]:
    """Project plain select items out of a column dict: the value column
    maps to the item's alias and subcolumns (__x/__y/__null) keep their
    suffix under the alias; dictionary vocabs never leak."""
    cols: Dict[str, np.ndarray] = {}
    for it in plain_items:
        src = it["name"]
        alias = it["alias"]
        found = False
        for k, v in columns.items():
            if k == src:
                cols[alias] = v
                found = True
            elif k.startswith(src + "__") and not k.endswith("__vocab"):
                cols[alias + k[len(src):]] = v
                found = True
        if not found:
            raise SqlError(f"Unknown column {src}")
    return cols


def _flatten_and(f: Optional[ast.Filter]) -> List[ast.Filter]:
    if f is None or isinstance(f, ast.Include):
        return []
    if isinstance(f, ast.And):
        return [p for c in f.children() for p in _flatten_and(c)]
    return [f]


def _strip_alias(f: ast.Filter) -> ast.Filter:
    """Rewrite 'alias.prop' references to bare 'prop' (in place — the
    nodes are fresh from this parse)."""
    for node in ast.walk(f):
        prop = getattr(node, "prop", None)
        if prop and "." in prop:
            node.prop = prop.split(".", 1)[1]
    return f


def _column_name(v) -> str:
    if v[0] != "col":
        raise SqlError(f"Expected column reference, got {v!r}")
    return v[1]


def _eval_geometry(v) -> Geometry:
    """Constant geometry expression -> Geometry."""
    if v[0] == "lit" and isinstance(v[1], str):
        return parse_wkt(v[1])
    if v[0] != "call":
        raise SqlError(f"Expected geometry expression, got {v!r}")
    _, fn, args = v
    if fn in ("st_geomfromwkt", "st_geomfromtext", "st_pointfromtext",
              "st_linefromtext", "st_polygonfromtext"):
        return parse_wkt(str(_lit(args[0])))
    if fn in ("st_makebbox", "st_makebox2d"):
        vals = [float(_lit(a)) for a in args]
        e = Envelope(*vals)
        return Polygon(
            [[e.xmin, e.ymin], [e.xmax, e.ymin], [e.xmax, e.ymax],
             [e.xmin, e.ymax], [e.xmin, e.ymin]]
        )
    if fn in ("st_point", "st_makepoint"):
        return Point(float(_lit(args[0])), float(_lit(args[1])))
    if fn == "st_geomfromgeohash":
        return st.st_geom_from_geohash(str(_lit(args[0])))
    raise SqlError(f"Unsupported geometry constructor {fn}")


def _col_and_geom(a, b) -> Tuple[str, Geometry, bool]:
    """(column, constant geometry, swapped): swapped=True when the column
    was the SECOND argument."""
    if a[0] == "col":
        return a[1], _eval_geometry(b), False
    if b[0] == "col":
        return b[1], _eval_geometry(a), True
    raise SqlError("Spatial predicate needs one column argument")


class SqlResult(SpatialFrame):
    """SpatialFrame + the pushed-down query plan (explain proves which
    index answered the WHERE clause)."""

    def __init__(self, columns, ft=None, plan=None):
        super().__init__(columns, ft)
        self.plan = plan

    @property
    def explain(self) -> str:
        return self.plan.explain if self.plan is not None else "(no plan)"


class SQLContext:
    """``SQLContext(store).sql("SELECT ... WHERE st_contains(...)")`` —
    the GeoMesaSparkSQL relation role over a TpuDataStore."""

    def __init__(self, store):
        self.store = store

    def sql(self, text: str) -> SqlResult:
        q = _Parser(text).parse()
        if q["join"] is not None:
            return self._execute_join(q)
        ft = self.store.get_schema(q["table"])
        return self._execute(ft, q)

    # -- JOIN (the Catalyst spatial-join relation, SQLRules.scala) -----------

    def _execute_join(self, q: dict) -> SqlResult:
        """Two-relation spatial join: single-alias WHERE conjuncts push
        down into EACH relation's index scan (per-relation CQL pushdown),
        the ON ST_* predicate folds into SpatialFrame.spatial_join, and
        the SELECT/GROUP/ORDER pipeline runs over the joined frame."""
        join = q["join"]
        la, ra = q["alias"], join["alias"]
        if la == ra:
            raise SqlError("JOIN aliases must differ")
        rels = {la: q["table"], ra: join["table"]}
        # split the WHERE into per-alias conjuncts
        conjuncts: Dict[str, List[ast.Filter]] = {la: [], ra: []}
        for part in _flatten_and(q["where"]):
            aliases = {p.split(".", 1)[0] for p in ast.properties(part) if "." in p}
            if len(aliases) != 1 or not aliases <= set(conjuncts):
                raise SqlError(
                    "JOIN WHERE predicates must reference exactly one alias"
                )
            conjuncts[aliases.pop()].append(_strip_alias(part))
        # ON predicate -> (left alias(points), right alias, predicate, dist)
        fn = join["fn"]
        args = join["args"]
        arg_alias = [
            a[1].split(".", 1)[0] if a[0] == "col" and "." in a[1] else None
            for a in args
        ]
        dist = None
        if fn == "st_dwithin":
            if len(args) < 3:
                raise SqlError("st_dwithin join needs a distance")
            dist = float(_lit(args[2]))
            pred = "dwithin"
            left, right = arg_alias[0], arg_alias[1]
        elif fn in ("st_intersects", "st_within", "st_contains"):
            # within(a, b): a inside b -> left=a drives; contains(a, b):
            # b inside a -> left=b. Point-left frames evaluate all three as
            # point-in-geometry; extent-left frames take the exact
            # geometry-geometry path in SpatialFrame.spatial_join.
            if fn == "st_within":
                pred = "within"
                left, right = arg_alias[0], arg_alias[1]
            elif fn == "st_contains":
                pred = "within"
                left, right = arg_alias[1], arg_alias[0]
            else:
                pred = "intersects"
                left, right = arg_alias[0], arg_alias[1]
        else:
            raise SqlError(f"Unsupported join predicate {fn}")
        if left is None or right is None or {left, right} != {la, ra}:
            raise SqlError("JOIN ON must reference both aliases' geometries")
        # intersects is symmetric: the POINT-typed relation drives the join
        if fn == "st_intersects":
            lft_pts = self.store.get_schema(rels[left]).is_points
            if not lft_pts and self.store.get_schema(rels[right]).is_points:
                left, right = right, left
        filters = {
            alias: (
                ast.and_option(conjuncts[alias])
                if conjuncts[alias]
                else ast.Include()
            )
            for alias in (la, ra)
        }
        # device-side join pushdown (ops/join.py): the point-in-polygon
        # and point-distance shapes ride the bucketed device kernels via
        # store.query_join — build side HBM-resident per schema
        # generation, probe side streamed, host degradation identical —
        # instead of materializing both frames and running the O(L*R)
        # host loop. Semantics match spatial_join exactly (boundary-
        # inclusive point-in-geometry / haversine radius, same
        # right-major pair order), so the SELECT pipeline downstream is
        # unchanged. Ineligible shapes (extent-left frames, point-point
        # containment, stores without query_join) keep the frame path.
        raw = None
        plans = {la: None, ra: None}
        lft = self.store.get_schema(rels[left])
        rft = self.store.get_schema(rels[right])
        device_shape = (
            getattr(self.store, "query_join", None) is not None
            and lft.is_points
            and (
                (pred in ("within", "intersects") and not rft.is_points)
                or (pred == "dwithin" and rft.is_points)
            )
        )
        if device_shape:
            from geomesa_tpu.ops.join import JoinError

            try:
                jr = self.store.query_join(
                    (rels[right], Query(filter=filters[right])),
                    (rels[left], Query(filter=filters[left])),
                    predicate="dwithin" if pred == "dwithin" else "contains",
                    radius_m=dist,
                )
            except JoinError:
                jr = None  # e.g. mixed build geometry: host frames below
            if jr is not None:
                plans[left] = jr.plan
                leftkeys = set(jr.probe.columns)
                rightkeys = set(jr.build.columns)
                raw = SpatialFrame(jr.raw_columns(suffix="_r"), jr.probe.ft)
        if raw is None:
            frames = {}
            for alias in (la, ra):
                res = self.store.query(rels[alias], Query(filter=filters[alias]))
                plans[alias] = res.plan
                frames[alias] = SpatialFrame(
                    res.columns if isinstance(res.columns, dict)
                    else res.columns.materialize(),
                    res.ft,
                )
            leftkeys = set(frames[left].columns)
            rightkeys = set(frames[right].columns)
            raw = frames[left].spatial_join(
                frames[right], predicate=pred, distance_m=dist, suffix="_r"
            )
        # canonicalize right-originated output columns DETERMINISTICALLY:
        # every right attribute becomes base_r (companions keep their
        # suffix: name__null -> name_r__null), whether or not it happened
        # to collide with a left column — qualified resolution must never
        # depend on the collision set
        cols = {}
        for k, v in raw.columns.items():
            if k in leftkeys:
                cols[k] = v
                continue
            orig = (
                k[:-2] if k.endswith("_r") and k[:-2] in rightkeys else k
            )
            if orig.startswith("__"):
                cols[k] = v  # __fid__ internals stay as produced
                continue
            base = orig.split("__", 1)[0]
            cols[base + "_r" + orig[len(base):]] = v
        joined = SpatialFrame(cols, raw.ft)

        def resolve(name: str) -> str:
            if "." not in name:
                raise SqlError(f"JOIN columns must be qualified: {name}")
            alias, col = name.split(".", 1)
            if alias == left:
                return col
            if alias == right:
                return col + "_r"
            raise SqlError(f"Unknown alias {alias}")

        items = []
        for it in q["items"]:
            it = dict(it)
            if it["kind"] == "stfn":
                # resolve qualified column args, compute over the joined
                # frame (the post-scan projection stage, like _execute)
                it["args"] = [
                    ("col", resolve(a[1])) if a[0] == "col" and "." in a[1] else a
                    for a in it["args"]
                ]
            elif it["kind"] == "col":
                src = resolve(it["name"])
                if it["alias"] == it["name"]:
                    # default output name: the bare column (AS overrides)
                    it["alias"] = it["name"].split(".", 1)[1]
                it["name"] = src
            elif it["kind"] == "agg" and it["arg"] != "*":
                it["arg"] = resolve(it["arg"])
            items.append(it)
        stfns = [it for it in items if it["kind"] == "stfn"]
        for it in stfns:
            joined = joined.with_column(
                it["alias"], _apply_stfn(joined, None, it["fn"], it["args"])
            )
        group = [resolve(g) if "." in g else g for g in q["group"]]
        aggs = [it for it in items if it["kind"] == "agg"]
        plain = [it for it in items if it["kind"] == "col"]
        star = any(it["kind"] == "star" for it in items)
        # group keys surface under their BARE names (same default as
        # plain select aliases): zname_r -> zname. Ambiguous bare
        # names (a.name + b.name) keep their resolved forms.
        bares = [g.split(".", 1)[1] for g in q["group"] if "." in g]
        renames = (
            {resolve(g): g.split(".", 1)[1] for g in q["group"] if "." in g}
            if len(set(bares)) == len(bares)
            else {}
        )
        having = (
            _resolve_having(q["having"], resolve, renames)
            if q["having"] is not None
            else None
        )
        if aggs or group or having is not None:
            stray_stfn = [
                it["alias"] for it in stfns if it["alias"] not in group
            ]
            if stray_stfn:
                raise SqlError(
                    f"Non-aggregated select expression(s) {stray_stfn} "
                    "must appear in GROUP BY"
                )
            aggs, hidden, aggmap = _with_having_aggs(having, aggs)
            out = self._aggregate(joined, group, aggs, plain)
            out = SpatialFrame(
                {renames.get(k, k): v for k, v in out.columns.items()}, out.ft
            )
            if having is not None:
                out = _apply_having(out, having, hidden, aggmap)
            for col, asc in reversed(q["order"]):
                key = col.split(".", 1)[1] if "." in col else col
                if key not in out.columns:
                    raise SqlError(f"ORDER BY references unknown column {col}")
                out = out.sort(key, asc)
        else:
            # sort on the FULL joined frame (aliases have not narrowed the
            # columns yet), then project; bare ORDER BY names may reference
            # the SELECT's output aliases (standard SQL)
            alias_src = {it["alias"]: it["name"] for it in plain}
            for col, asc in reversed(q["order"]):
                key = resolve(col) if "." in col else alias_src.get(col, col)
                if key not in joined.columns:
                    raise SqlError(f"ORDER BY references unknown column {col}")
                joined = joined.sort(key, asc)
            if star:
                out = joined
            else:
                cols = _project_plain(joined.columns, plain)
                for it in stfns:
                    cols[it["alias"]] = joined.columns[it["alias"]]
                out = SpatialFrame(cols, joined.ft)
        if q["limit"] is not None:
            out = SpatialFrame(
                {k: v[: q["limit"]] for k, v in out.columns.items()}, out.ft
            )
        return SqlResult(out.columns, out.ft, plans[left])

    # -- execution -----------------------------------------------------------

    def _execute(self, ft, q: dict) -> SqlResult:
        items = q["items"]
        aggs = [it for it in items if it["kind"] == "agg"]
        plain = [it for it in items if it["kind"] == "col"]
        stfns = [it for it in items if it["kind"] == "stfn"]
        star = any(it["kind"] == "star" for it in items)

        # COUNT(*)-only fast path: no rows leave the store at all —
        # store.count rides the device mask-sum (executor.count_scan)
        # when the WHERE is device-decidable, the ordinary scan + len
        # otherwise (Spark's count pushdown role)
        if (
            len(aggs) == 1 and not plain and not stfns and not star
            and not q["group"] and q["having"] is None and not q["order"]
            and aggs[0]["fn"] == "count" and aggs[0]["arg"] == "*"
        ):
            cq = Query(
                filter=q["where"] if q["where"] is not None else ast.Include()
            )
            cnt = None
            count = getattr(self.store, "count", None)
            if callable(count):
                import inspect

                try:
                    takes_filter = len(
                        inspect.signature(count).parameters
                    ) >= 2
                except (TypeError, ValueError):
                    takes_filter = True
                if takes_filter:
                    cnt = count(ft.name, cq)
            if cnt is not None:
                # .explain must still prove which index would answer the
                # WHERE (SqlResult.plan's stated purpose); .ft is None
                # exactly as _aggregate's global-aggregate frames are
                plan = None
                plan_cached = getattr(self.store, "_plan_cached", None)
                if callable(plan_cached):
                    try:
                        plan = plan_cached(ft.name, cq)
                    except Exception:  # noqa: BLE001 - explain is advisory
                        plan = None
                cols = {aggs[0]["alias"]: np.asarray([cnt])}
                if q["limit"] is not None:
                    cols = {k: v[: q["limit"]] for k, v in cols.items()}
                return SqlResult(cols, None, plan)

        # sketch push-down: global COUNT(*)/MIN/MAX and single-key
        # GROUP BY + COUNT(*) ride the stats hint — the store answers from
        # per-code count histograms (device stats scan on accelerators)
        # and rows never leave the scan (Spark's aggregate-pushdown role)
        sk = self._stats_pushdown(ft, q, aggs, plain, stfns, star)
        if sk is not None:
            return sk

        # projection pushdown: only the columns the SELECT needs leave the
        # scan (group keys, agg sources, plain columns, st-fn inputs)
        props: Optional[List[str]] = None
        if not star:
            needed = set(q["group"])
            needed.update(it["name"] for it in plain)
            needed.update(it["arg"] for it in aggs if it["arg"] != "*")
            if q["having"] is not None:
                hterms: list = []
                _having_agg_terms(q["having"], hterms)
                needed.update(arg for _t, _fn, arg in hterms if arg != "*")
            for it in stfns:
                needed.update(a[1] for a in it["args"] if a[0] == "col")
            if aggs and not needed:
                geom = ft.default_geometry
                needed.add(geom.name if geom is not None else ft.attributes[0].name)
            props = sorted(needed)
        # sort pushes into the scan ONLY when it orders real schema
        # attributes of a plain (non-aggregated) select — ORDER BY over an
        # agg/select alias sorts the client-side result instead
        push_sort = (
            q["order"]
            and not aggs
            and not q["group"]
            and all(ft.has(col) for col, _ in q["order"])
        )
        query = Query(
            filter=q["where"] if q["where"] is not None else ast.Include(),
            properties=props,
            sort_by=q["order"] if push_sort else None,
            max_features=(
                q["limit"] if push_sort or (
                    not q["order"] and not aggs and not q["group"]
                ) else None
            ),
        )
        res = self.store.query(ft.name, query)
        frame = SpatialFrame(
            res.columns if isinstance(res.columns, dict) else res.columns.materialize(),
            res.ft,
        )
        # scalar ST_* projections (computed client-side, like Spark's
        # post-scan stage)
        for it in stfns:
            frame = frame.with_column(
                it["alias"], _apply_stfn(frame, ft, it["fn"], it["args"])
            )
        if aggs or q["group"] or q["having"] is not None:
            stray_stfn = [
                it["alias"] for it in stfns if it["alias"] not in q["group"]
            ]
            if stray_stfn:
                raise SqlError(
                    f"Non-aggregated select expression(s) {stray_stfn} "
                    "must appear in GROUP BY"
                )
            aggs, hidden, aggmap = _with_having_aggs(q["having"], aggs)
            out = self._aggregate(frame, q["group"], aggs, plain)
            if q["having"] is not None:
                out = _apply_having(out, q["having"], hidden, aggmap)
            if q["order"]:
                for col, asc in reversed(q["order"]):
                    if col in out.columns:
                        out = out.sort(col, asc)
            if q["limit"] is not None:
                out = SqlResult(
                    {k: v[: q["limit"]] for k, v in out.columns.items()},
                    out.ft, res.plan,
                )
                return out
            return SqlResult(out.columns, out.ft, res.plan)
        if not star:
            cols = _project_plain(frame.columns, plain)
            for it in stfns:
                cols[it["alias"]] = frame.columns[it["alias"]]
            frame = SpatialFrame(cols, frame.ft)
        if q["order"] and not push_sort:
            # ORDER BY over aliases/derived columns: client-side sort
            for col, asc in reversed(q["order"]):
                if col in frame.columns:
                    frame = frame.sort(col, asc)
                else:
                    raise SqlError(f"ORDER BY references unknown column {col}")
            if q["limit"] is not None:
                frame = SpatialFrame(
                    {k: v[: q["limit"]] for k, v in frame.columns.items()},
                    frame.ft,
                )
        return SqlResult(frame.columns, frame.ft, res.plan)

    def _stats_pushdown(self, ft, q: dict, aggs, plain, stfns, star):
        """SqlResult for aggregate shapes the stats sketches answer
        exactly, or None to take the ordinary extract-then-aggregate
        path. Supported: global COUNT(*)/MIN(a)/MAX(a) combinations, and
        ``SELECT key, COUNT(*) ... GROUP BY key``. MIN/MAX ignore nulls
        (SQL semantics, matching the null-excluding rank-code planes);
        an empty result yields 0 like _aggregate's empty shape."""
        if star or stfns or q["having"] is not None or q["order"]:
            return None
        group = q["group"]
        if group:
            if (
                len(group) != 1 or len(aggs) != 1
                or aggs[0]["fn"] != "count" or aggs[0]["arg"] != "*"
                or [it["name"] for it in plain] != group
                or not ft.has(group[0])
            ):
                return None
            spec = f"GroupBy({group[0]},Count())"
        else:
            if not aggs or plain:
                return None
            parts = []
            for a in aggs:
                if a["fn"] == "count" and a["arg"] == "*":
                    parts.append("Count()")
                elif (
                    a["fn"] in ("min", "max")
                    and a["arg"] != "*" and ft.has(a["arg"])
                ):
                    parts.append(f"MinMax({a['arg']})")
                else:
                    return None
            spec = ";".join(dict.fromkeys(parts))
        cq = Query(
            filter=q["where"] if q["where"] is not None else ast.Include(),
            hints={"stats": spec},
        )
        try:
            res = self.store.query(ft.name, cq)
        except Exception:  # noqa: BLE001 - store without stats hints
            return None
        stat = getattr(res, "aggregate", {}).get("stats")
        if stat is None:
            return None
        stats = stat.stats if hasattr(stat, "stats") else [stat]
        if group:
            gb = stats[0]
            keys = sorted(gb.groups)  # group_by emits np.unique order
            cols = {
                group[0]: np.asarray(keys),
                aggs[0]["alias"]: np.asarray(
                    [gb.groups[k].count for k in keys], dtype=np.int64
                ),
            }
        else:
            by_attr = {
                getattr(s, "attribute", None): s
                for s in stats if s.kind == "minmax"
            }
            total = next((s.count for s in stats if s.kind == "count"), None)
            cols = {}
            for a in aggs:
                if a["fn"] == "count":
                    cols[a["alias"]] = np.asarray([int(total)])
                else:
                    mm = by_attr[a["arg"]]
                    v = mm.min if a["fn"] == "min" else mm.max
                    cols[a["alias"]] = np.asarray([v if v is not None else 0])
        if q["limit"] is not None:
            cols = {k: v[: q["limit"]] for k, v in cols.items()}
        # aggregate results carry no feature type, like _aggregate's frames
        return SqlResult(cols, None, res.plan)

    @staticmethod
    def _aggregate(frame: SpatialFrame, group: List[str], aggs, plain) -> SpatialFrame:
        fn_map = {"count": "count", "sum": "sum", "avg": "mean",
                  "mean": "mean", "min": "min", "max": "max"}
        stray = [it["name"] for it in plain if it["name"] not in group]
        if stray:
            raise SqlError(
                f"Non-aggregated column(s) {stray} must appear in GROUP BY"
            )
        if group:
            spec = {}
            for it in aggs:
                src = it["arg"]
                if src == "*":
                    src = group[0]
                spec[it["alias"]] = (fn_map[it["fn"]], src)
            return frame.group_by(group, spec)
        # global aggregate: one row
        cols: Dict[str, np.ndarray] = {}
        n = len(frame)
        for it in aggs:
            if it["fn"] == "count":
                cols[it["alias"]] = np.asarray([n])
            else:
                src = frame.columns[it["arg"]]
                cols[it["alias"]] = np.asarray(
                    [SpatialFrame._AGGS[fn_map[it["fn"]]](src) if n else 0]
                )
        return SpatialFrame(cols, None)


def _apply_stfn(frame: SpatialFrame, ft, fn: str, args: list) -> np.ndarray:
    """Scalar ST_* select expressions over result columns. ft may be None
    (JOIN queries) — every column argument must then be explicit."""
    geom = (
        ft.default_geometry.name
        if ft is not None and ft.default_geometry is not None
        else None
    )

    def coord(axis: str, col: str) -> np.ndarray:
        got = frame.columns.get(f"{col}__{axis}")
        if got is None:
            raise SqlError(f"{fn} needs point column {col}")
        return got

    if fn in ("st_x", "st_y"):
        col = args[0][1] if args and args[0][0] == "col" else geom
        return coord("x" if fn == "st_x" else "y", col)
    if fn == "st_geohash":
        col = args[0][1] if args and args[0][0] == "col" else geom
        prec = int(_lit(args[1])) if len(args) > 1 else 9
        return st.st_geohash(coord("x", col), coord("y", col), prec)
    raise SqlError(f"Unsupported select function {fn}")
