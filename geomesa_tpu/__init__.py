"""geomesa-tpu: a TPU-native spatio-temporal indexing and query framework.

A from-scratch rebuild of the capabilities of GeoMesa (reference: /root/reference)
designed for JAX/XLA/TPU: columnar feature blocks in HBM, vectorized space-filling
curve kernels, batched range decomposition, device-side push-down filters and
aggregations, and multi-chip execution via ``jax.sharding`` meshes.

Layer map (mirrors SURVEY.md; COMPONENTS.md maps every reference component):
  - ``geomesa_tpu.curve``    -- L0 curve math (Z2/Z3/XZ2/XZ3, binned time)
  - ``geomesa_tpu.geom``     -- geometry model + predicates
  - ``geomesa_tpu.schema``   -- feature types (SimpleFeatureTypes analog)
  - ``geomesa_tpu.filter``   -- CQL-style filter AST, extraction, splitting
  - ``geomesa_tpu.index``    -- key spaces, strategies, query planner, transforms
  - ``geomesa_tpu.store``    -- columnar block store + memory/fs datastores
  - ``geomesa_tpu.ops``      -- JAX/Pallas device kernels (filter/aggregate)
  - ``geomesa_tpu.parallel`` -- mesh sharding + the device scan executor
  - ``geomesa_tpu.stats``    -- data sketches + cost estimation
  - ``geomesa_tpu.stream``   -- live/lambda tiers (Kafka analog)
  - ``geomesa_tpu.security`` -- visibility expressions + auth providers
  - ``geomesa_tpu.process``  -- kNN/proximity/tube/route/track processes
  - ``geomesa_tpu.compute``  -- SpatialFrame + ST_* (Spark SQL analog)
  - ``geomesa_tpu.arrow``    -- Arrow interchange + delta dictionaries
  - ``geomesa_tpu.raster``   -- raster chip store + mosaicking
  - ``geomesa_tpu.tools``    -- converters, bulk ingest, exports, CLI
  - ``geomesa_tpu.utils``    -- geohash, avro, config tiers, audit/metrics
"""

__version__ = "0.2.0"
