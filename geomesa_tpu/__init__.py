"""geomesa-tpu: a TPU-native spatio-temporal indexing and query framework.

A from-scratch rebuild of the capabilities of GeoMesa (reference: /root/reference)
designed for JAX/XLA/TPU: columnar feature blocks in HBM, vectorized space-filling
curve kernels, batched range decomposition, device-side push-down filters and
aggregations, and multi-chip execution via ``jax.sharding`` meshes.

Layer map (mirrors SURVEY.md):
  - ``geomesa_tpu.curve``    -- L0 curve math (Z2/Z3/XZ2/XZ3, binned time)
  - ``geomesa_tpu.geom``     -- geometry model + predicates
  - ``geomesa_tpu.schema``   -- feature types (SimpleFeatureTypes analog)
  - ``geomesa_tpu.filter``   -- CQL-style filter AST, extraction, splitting
  - ``geomesa_tpu.index``    -- key spaces, strategies, query planner
  - ``geomesa_tpu.store``    -- columnar block store + datastores
  - ``geomesa_tpu.ops``      -- JAX device kernels (filter/aggregate)
  - ``geomesa_tpu.parallel`` -- mesh sharding + distributed execution
  - ``geomesa_tpu.stats``    -- data sketches + cost estimation
  - ``geomesa_tpu.convert``  -- ingest converters
  - ``geomesa_tpu.tools``    -- CLI
"""

__version__ = "0.1.0"
