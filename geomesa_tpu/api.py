"""Simple value-store facade (the geomesa-native-api analog).

Reference: geomesa-native-api GeoMesaIndex.java — a Java-friendly wrapper
hiding GeoTools: put(id, value, geometry, date), query(bbox/time) -> values,
with a pluggable ValueSerializer. Same shape here for callers that don't
want the full datastore surface.
"""

from __future__ import annotations

import json
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from geomesa_tpu.geom.base import Point
from geomesa_tpu.schema.featuretype import parse_spec
from geomesa_tpu.store.datastore import TpuDataStore

_SPEC = "payload:String,dtg:Date,*geom:Point:srid=4326"


class ValueSerializer:
    def to_bytes(self, value: Any) -> str:
        raise NotImplementedError

    def from_bytes(self, data: str) -> Any:
        raise NotImplementedError


class JsonValueSerializer(ValueSerializer):
    def to_bytes(self, value: Any) -> str:
        return json.dumps(value)

    def from_bytes(self, data: str) -> Any:
        return json.loads(data)


class GeoMesaIndex:
    """put/get/query over (id, value, lon, lat, time)."""

    def __init__(
        self,
        name: str = "values",
        store: Optional[TpuDataStore] = None,
        serializer: Optional[ValueSerializer] = None,
    ):
        self.name = name
        self.store = store or TpuDataStore()
        self.serializer = serializer or JsonValueSerializer()
        self.store.create_schema(parse_spec(name, _SPEC))

    def put(self, fid: str, value: Any, x: float, y: float, t_ms: int) -> str:
        with self.store.writer(self.name) as w:
            return w.write(
                [self.serializer.to_bytes(value), int(t_ms), Point(x, y)], fid=fid
            )

    def put_batch(self, items) -> None:
        """items: iterable of (fid, value, x, y, t_ms)."""
        with self.store.writer(self.name) as w:
            for fid, value, x, y, t in items:
                w.write([self.serializer.to_bytes(value), int(t), Point(x, y)], fid=fid)

    def delete(self, fid: str) -> None:
        self.store.delete_features(self.name, [fid])

    def query(
        self,
        bbox: Optional[Tuple[float, float, float, float]] = None,
        time_range_ms: Optional[Tuple[int, int]] = None,
    ) -> List[Tuple[str, Any]]:
        parts = []
        if bbox:
            parts.append(f"bbox(geom, {bbox[0]}, {bbox[1]}, {bbox[2]}, {bbox[3]})")
        if time_range_ms:
            lo = np.datetime64(int(time_range_ms[0]), "ms").item().isoformat() + "Z"
            hi = np.datetime64(int(time_range_ms[1]), "ms").item().isoformat() + "Z"
            parts.append(f"dtg DURING {lo}/{hi}")
        cql = " AND ".join(parts) or "INCLUDE"
        res = self.store.query(self.name, cql)
        payloads = res.columns["payload"]
        return [
            (str(fid), self.serializer.from_bytes(payloads[i]))
            for i, fid in enumerate(res.fids)
        ]
