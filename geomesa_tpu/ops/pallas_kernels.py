"""Pallas TPU kernels for the scan hot loop.

The fused candidate mask is the framework's per-row hot op (the tserver
Z3Iterator seek/next loop, accumulo/iterators/Z3Iterator.scala:42-65). The
XLA version in ops/filters.py materializes an [N, K] broadcast; this Pallas
kernel streams row tiles through VMEM and accumulates the per-box/window
tests in registers, so HBM traffic is one read of each column + one packed
write — the memory-bound optimum.

Shapes: rows padded to a multiple of the 2D tile (8, 128); boxes [K, 4] and
windows [W, 3] are small and live in VMEM replicated per tile. On non-TPU
backends ``interpret=True`` keeps the kernel testable (conftest's CPU mesh).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

TILE = 8 * 128  # one (8, 128) vreg-shaped row tile per grid step


def _z3_mask_kernel(xi_ref, yi_ref, bins_ref, offs_ref, valid_ref, boxes_ref,
                    windows_ref, out_ref, *, k: int, w: int):
    xi = xi_ref[...]
    yi = yi_ref[...]
    bins = bins_ref[...]
    offs = offs_ref[...]
    spatial = jnp.zeros(xi.shape, dtype=jnp.bool_)
    for j in range(k):  # k/w are small static pads; unrolled vector ops
        spatial = spatial | (
            (xi >= boxes_ref[j, 0])
            & (xi <= boxes_ref[j, 2])
            & (yi >= boxes_ref[j, 1])
            & (yi <= boxes_ref[j, 3])
        )
    temporal = jnp.zeros(xi.shape, dtype=jnp.bool_)
    for j in range(w):
        temporal = temporal | (
            (bins == windows_ref[j, 0])
            & (offs >= windows_ref[j, 1])
            & (offs <= windows_ref[j, 2])
        )
    out_ref[...] = valid_ref[...] & spatial & temporal


@functools.partial(jax.jit, static_argnames=("interpret",))
def _run(xi, yi, bins, offs, valid, boxes, windows, interpret):
    n = xi.shape[0]
    rows = n // 128
    shape = (rows, 128)
    grid = (rows // 8,)
    row_spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    small = lambda a: pl.BlockSpec(a.shape, lambda i: (0, 0))
    kern = functools.partial(
        _z3_mask_kernel, k=boxes.shape[0], w=windows.shape[0]
    )
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec, row_spec, row_spec,
                  small(boxes), small(windows)],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(shape, jnp.bool_),
        interpret=interpret,
    )(
        xi.reshape(shape),
        yi.reshape(shape),
        bins.reshape(shape),
        offs.reshape(shape),
        valid.reshape(shape),
        boxes,
        windows,
    )
    return out.reshape(n)


def z3_query_mask_pallas(xi, yi, bins, offs, valid, boxes, windows,
                         interpret: bool | None = None):
    """Drop-in for ops.filters.z3_query_mask; rows must be TILE-padded."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if xi.shape[0] % TILE:
        raise ValueError(f"rows must be padded to {TILE}")
    return _run(
        jnp.asarray(xi, jnp.int32),
        jnp.asarray(yi, jnp.int32),
        jnp.asarray(bins, jnp.int32),
        jnp.asarray(offs, jnp.int32),
        jnp.asarray(valid),
        jnp.asarray(boxes, jnp.int32),
        jnp.asarray(windows, jnp.int32),
        interpret,
    )
