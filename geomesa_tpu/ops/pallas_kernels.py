"""Pallas TPU kernels for the scan + aggregation hot loops.

The fused candidate mask is the framework's per-row hot op (the tserver
Z3Iterator seek/next loop, accumulo/iterators/Z3Iterator.scala:42-65; the
Z2/XZ variants, filters/Z2Filter.scala:18-20, XZ2IndexKeySpace.scala:26+).
The XLA version in ops/filters.py materializes an [N, K] broadcast; these
Pallas kernels stream row tiles through VMEM and accumulate the per-box /
window tests in registers, so HBM traffic is one read of each column + one
bool write — the memory-bound optimum.

The density kernel is the DensityScan analog (iterators/DensityScan.scala:
30-59): instead of a scatter-add (which serializes on TPU), each row tile
builds weighted one-hot row/col matrices and accumulates the grid as an
outer-product matmul R^T @ C on the MXU — the systolic array does the
scatter.

Shapes: rows padded to a multiple of TILE; boxes [K, 4] and windows [W, 3]
are small and live in VMEM replicated per tile. On non-TPU backends
``interpret=True`` keeps the kernels testable (conftest's CPU mesh).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

TILE = 8 * 128  # one (8, 128) vreg-shaped row tile per grid step
# one-hot density matmul VMEM budget: R[TILE,H] + C[TILE,W] + out[H,W] f32
DENSITY_MAX_DIM = 512


def _row_spec():
    return pl.BlockSpec((8, 128), lambda i: (i, 0))


def _small(a):
    return pl.BlockSpec(a.shape, lambda i: (0, 0))


def _contains(x, y, boxes_ref, k):
    """Any-box containment; dtype-generic (int curve domain or raw f32)."""
    m = jnp.zeros(x.shape, dtype=jnp.bool_)
    for j in range(k):  # k is a small static pad; unrolled vector ops
        m = m | (
            (x >= boxes_ref[j, 0])
            & (x <= boxes_ref[j, 2])
            & (y >= boxes_ref[j, 1])
            & (y <= boxes_ref[j, 3])
        )
    return m


def _temporal(bins, offs, windows_ref, w):
    m = jnp.zeros(bins.shape, dtype=jnp.bool_)
    for j in range(w):
        m = m | (
            (bins == windows_ref[j, 0])
            & (offs >= windows_ref[j, 1])
            & (offs <= windows_ref[j, 2])
        )
    return m


def _overlap(bxmin, bymin, bxmax, bymax, boxes_ref, k):
    m = jnp.zeros(bxmin.shape, dtype=jnp.bool_)
    for j in range(k):
        m = m | (
            (bxmin <= boxes_ref[j, 2])
            & (bxmax >= boxes_ref[j, 0])
            & (bymin <= boxes_ref[j, 3])
            & (bymax >= boxes_ref[j, 1])
        )
    return m


# -- candidate-mask kernels -------------------------------------------------


def _z3_mask_kernel(xi_ref, yi_ref, bins_ref, offs_ref, valid_ref, boxes_ref,
                    windows_ref, out_ref, *, k: int, w: int):
    spatial = _contains(xi_ref[...], yi_ref[...], boxes_ref, k)
    temporal = _temporal(bins_ref[...], offs_ref[...], windows_ref, w)
    out_ref[...] = valid_ref[...] & spatial & temporal


def _z2_mask_kernel(xi_ref, yi_ref, valid_ref, boxes_ref, out_ref, *, k: int):
    out_ref[...] = valid_ref[...] & _contains(xi_ref[...], yi_ref[...], boxes_ref, k)


def _xz2_mask_kernel(bxmin_ref, bymin_ref, bxmax_ref, bymax_ref, valid_ref,
                     boxes_ref, out_ref, *, k: int):
    out_ref[...] = valid_ref[...] & _overlap(
        bxmin_ref[...], bymin_ref[...], bxmax_ref[...], bymax_ref[...], boxes_ref, k
    )


def _xz3_mask_kernel(bxmin_ref, bymin_ref, bxmax_ref, bymax_ref, bins_ref,
                     offs_ref, valid_ref, boxes_ref, windows_ref, out_ref,
                     *, k: int, w: int):
    overlap = _overlap(
        bxmin_ref[...], bymin_ref[...], bxmax_ref[...], bymax_ref[...], boxes_ref, k
    )
    temporal = _temporal(bins_ref[...], offs_ref[...], windows_ref, w)
    out_ref[...] = valid_ref[...] & overlap & temporal


def _run_mask(kernel, row_args, small_args, interpret):
    """Common pallas_call driver: row columns tiled (8, 128), small query
    descriptors replicated whole into VMEM."""
    n = row_args[0].shape[0]
    if n % TILE:
        raise ValueError(f"rows must be padded to {TILE}")
    rows = n // 128
    shape = (rows, 128)
    grid = (rows // 8,)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_row_spec()] * len(row_args) + [_small(a) for a in small_args],
        out_specs=_row_spec(),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.bool_),
        interpret=interpret,
    )(*[a.reshape(shape) for a in row_args], *small_args)
    return out.reshape(n)


def _auto_interpret(interpret):
    return jax.default_backend() != "tpu" if interpret is None else interpret


@functools.partial(jax.jit, static_argnames=("interpret",))
def _z3_run(xi, yi, bins, offs, valid, boxes, windows, interpret):
    kern = functools.partial(_z3_mask_kernel, k=boxes.shape[0], w=windows.shape[0])
    return _run_mask(kern, (xi, yi, bins, offs, valid), (boxes, windows), interpret)


def z3_query_mask_pallas(xi, yi, bins, offs, valid, boxes, windows,
                         interpret: bool | None = None):
    """Drop-in for ops.filters.z3_query_mask; rows must be TILE-padded."""
    return _z3_run(
        jnp.asarray(xi, jnp.int32),
        jnp.asarray(yi, jnp.int32),
        jnp.asarray(bins, jnp.int32),
        jnp.asarray(offs, jnp.int32),
        jnp.asarray(valid),
        jnp.asarray(boxes, jnp.int32),
        jnp.asarray(windows, jnp.int32),
        _auto_interpret(interpret),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _z2_run(xi, yi, valid, boxes, interpret):
    kern = functools.partial(_z2_mask_kernel, k=boxes.shape[0])
    return _run_mask(kern, (xi, yi, valid), (boxes,), interpret)


def z2_query_mask_pallas(xi, yi, valid, boxes, interpret: bool | None = None):
    """Drop-in for ops.filters.z2_query_mask; rows must be TILE-padded."""
    return _z2_run(
        jnp.asarray(xi, jnp.int32),
        jnp.asarray(yi, jnp.int32),
        jnp.asarray(valid),
        jnp.asarray(boxes, jnp.int32),
        _auto_interpret(interpret),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _xz2_run(bxmin, bymin, bxmax, bymax, valid, boxes, interpret):
    kern = functools.partial(_xz2_mask_kernel, k=boxes.shape[0])
    return _run_mask(kern, (bxmin, bymin, bxmax, bymax, valid), (boxes,), interpret)


def xz2_overlap_mask_pallas(bxmin, bymin, bxmax, bymax, valid, boxes,
                            interpret: bool | None = None):
    """Drop-in for ops.filters.bbox_overlap_mask (f32 extent test)."""
    return _xz2_run(
        jnp.asarray(bxmin, jnp.float32),
        jnp.asarray(bymin, jnp.float32),
        jnp.asarray(bxmax, jnp.float32),
        jnp.asarray(bymax, jnp.float32),
        jnp.asarray(valid),
        jnp.asarray(boxes, jnp.float32),
        _auto_interpret(interpret),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _xz3_run(bxmin, bymin, bxmax, bymax, bins, offs, valid, boxes, windows, interpret):
    kern = functools.partial(_xz3_mask_kernel, k=boxes.shape[0], w=windows.shape[0])
    return _run_mask(
        kern, (bxmin, bymin, bxmax, bymax, bins, offs, valid), (boxes, windows), interpret
    )


def xz3_overlap_mask_pallas(bxmin, bymin, bxmax, bymax, bins, offs, valid,
                            boxes, windows, interpret: bool | None = None):
    """XZ3: f32 extent overlap AND int (bin, offset) window test."""
    return _xz3_run(
        jnp.asarray(bxmin, jnp.float32),
        jnp.asarray(bymin, jnp.float32),
        jnp.asarray(bxmax, jnp.float32),
        jnp.asarray(bymax, jnp.float32),
        jnp.asarray(bins, jnp.int32),
        jnp.asarray(offs, jnp.int32),
        jnp.asarray(valid),
        jnp.asarray(boxes, jnp.float32),
        jnp.asarray(windows, jnp.int32),
        _auto_interpret(interpret),
    )


# -- density: one-hot outer-product matmul on the MXU -----------------------


def _density_kernel(x_ref, y_ref, bins_ref, offs_ref, valid_ref, boxes_ref,
                    windows_ref, env_ref, out_ref, *, k: int, w: int,
                    width: int, height: int, with_time: bool):
    """Accumulate the [H, W] density grid across row-tile grid steps.

    grid[r, c] = sum_i weight_i * [row_i == r] * [col_i == c]
               = (W ⊙ onehot_rows)^T @ onehot_cols   — an MXU matmul,
    replacing the data-dependent scatter-add the reference does per tserver
    (DensityScan.scala:30-59 sparse map + GridSnap).
    """
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    from geomesa_tpu.ops.aggregations import grid_snap_indices

    x = x_ref[...]  # (TILE, 1) f32
    y = y_ref[...]
    # exact f32 spatial predicate (raw-domain boxes)
    m = _contains(x, y, boxes_ref, k)
    if with_time:
        m = m & _temporal(bins_ref[...], offs_ref[...], windows_ref, w)
    m = m & valid_ref[...]
    # single shared GridSnap implementation (aggregations.grid_snap_indices)
    # keeps XLA-vs-Pallas density parity by construction
    col, row, in_env = grid_snap_indices(x, y, env_ref[0], width, height)
    weight = jnp.where(m & in_env, jnp.float32(1.0), jnp.float32(0.0))
    rows_iota = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], height), 1)
    cols_iota = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], width), 1)
    r_onehot = jnp.where(row == rows_iota, weight, jnp.float32(0.0))  # (T, H)
    c_onehot = jnp.where(col == cols_iota, jnp.float32(1.0), jnp.float32(0.0))
    out_ref[...] += jax.lax.dot_general(
        r_onehot,
        c_onehot,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("width", "height", "with_time", "interpret"))
def _density_run(x, y, bins, offs, valid, boxes, windows, env,
                 width, height, with_time, interpret):
    n = x.shape[0]
    if n % TILE:
        raise ValueError(f"rows must be padded to {TILE}")
    col_spec = pl.BlockSpec((TILE, 1), lambda i: (i, 0))
    shape = (n, 1)
    kern = functools.partial(
        _density_kernel,
        k=boxes.shape[0],
        w=windows.shape[0],
        width=width,
        height=height,
        with_time=with_time,
    )
    out_spec = pl.BlockSpec((height, width), lambda i: (0, 0))
    env2 = env.reshape(1, 4)
    return pl.pallas_call(
        kern,
        grid=(n // TILE,),
        in_specs=[col_spec] * 5 + [_small(boxes), _small(windows), _small(env2)],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((height, width), jnp.float32),
        interpret=interpret,
    )(
        x.reshape(shape),
        y.reshape(shape),
        bins.reshape(shape),
        offs.reshape(shape),
        valid.reshape(shape),
        boxes,
        windows,
        env2,
    )


def density_grid_pallas(x, y, bins, offs, valid, boxes, windows, env,
                        width: int, height: int, with_time: bool,
                        interpret: bool | None = None):
    """Fused mask + density grid; (bins, offs, windows) ignored unless
    ``with_time``. width/height must be <= DENSITY_MAX_DIM (VMEM budget)."""
    if width > DENSITY_MAX_DIM or height > DENSITY_MAX_DIM:
        raise ValueError(f"grid dims must be <= {DENSITY_MAX_DIM}")
    n = x.shape[0]
    if bins is None:
        bins = jnp.zeros(n, jnp.int32)
        offs = jnp.zeros(n, jnp.int32)
        windows = jnp.zeros((1, 3), jnp.int32)
    return _density_run(
        jnp.asarray(x, jnp.float32),
        jnp.asarray(y, jnp.float32),
        jnp.asarray(bins, jnp.int32),
        jnp.asarray(offs, jnp.int32),
        jnp.asarray(valid),
        jnp.asarray(boxes, jnp.float32),
        jnp.asarray(windows, jnp.int32),
        jnp.asarray(env, jnp.float32),
        width,
        height,
        with_time,
        _auto_interpret(interpret),
    )
