"""Device-side spatial joins: point-in-polygon and distance joins.

The enrichment query class ("which events fall inside which geofences")
the predicate-scan pipeline cannot answer. The shape follows the
adaptive-join literature (PAPERS.md: "Adaptive Geospatial Joins for
Modern Hardware", "3DPipe"): a grid/Z-bucketed BUILD side resident on
device, a streamed PROBE side, and adaptive repartitioning when skew
blows a bucket past the pad budget.

Layout
------
The build side (geofence polygons for ``contains``, points for
``dwithin``) buckets into a low-resolution z2 grid
(``geomesa.join.bucket.bits`` per dimension). Each geometry lands in
every cell its radius-and-epsilon expanded envelope overlaps; any
bucket holding more than ``geomesa.join.skew.threshold`` geometries
quad-splits into finer cells (up to ``geomesa.join.split.depth``
levels) — the devstats pad gauges are fed per upload, and the split
keeps every kernel dispatch inside one shared pow2 candidate bucket
instead of letting one hot cluster pad every probe chunk to its size.
Geometry edge lists (``[G, E_pad, 4]``), build coordinates, and the
bucket candidate matrix (``[B, C_pad]``, -1 padded) upload ONCE per
schema generation through the mesh dispatch path and stay HBM-resident
in a TTL'd per-store cache (``geomesa.join.cache.ttl``).

Kernels and exactness
---------------------
Probe points stream through the segment-upload path
(``parallel/executor.join_upload``) ``geomesa.join.probe.chunk`` rows
at a time, NaN-padded to pow2 groups per bucket. The f32 kernels
(``join_pip`` even-odd ray cast, ``join_dwithin`` haversine) return a
DUAL mask per (probe, candidate) pair: ``accept`` (decidably matching,
safely away from any boundary) and ``check`` (within the boundary band
— the GridSnap/normalization epsilon of ops/geometry plus the f32
slack). Accepted pairs are final; band pairs get the exact f64 host
predicate. The host reference join routes probes through the SAME
bucket structure and applies the same exact predicates, so the device
path and the host degradation path return identical pairs by
construction — the repo's parity-under-faults invariant extends to the
join query class.

Failure envelope
----------------
``join.build`` (bucketing + device upload) and ``join.probe`` (per
chunk) are named fault points paired with spans and deadline checks.
Any device failure degrades the WHOLE join to the host reference path
(identical pairs) and trips the session flag via
``GEOMESA_JOIN_DEVICE`` semantics (auto | 0=host | 1=always retry
device), mirroring the density/stats push-down autos.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.geom.base import Geometry, MultiPolygon, Polygon
from geomesa_tpu.ops.geometry import (
    polygon_edges,
    snap_epsilon_deg,
    snap_epsilon_m,
)
from geomesa_tpu.utils import audit as audit_mod
from geomesa_tpu.utils import deadline, faults, trace
from geomesa_tpu.utils import plans as plans_mod
from geomesa_tpu.utils.devstats import devstats_metrics, instrumented_jit

# the point-in-polygon boundary band, degrees. Pairs whose probe point
# sits within this distance of ANY build edge are host-verified in f64;
# the f32 ray cast is trusted only beyond it. 1e-3 deg (~110 m) safely
# dominates worst-case f32 coordinate arithmetic error at world scale
# (ulp(360) ~ 3e-5 deg, amplified a few x by the edge-intersection
# division) while keeping the exact-check band a sliver of any real
# geofence. The curve layer's snap epsilon folds in for index-derived
# coordinates.
PIP_BAND_DEG = 1e-3

# pow2 floor for probe-group padding: small groups bucket to one shape
GROUP_FLOOR = 64
# build-cache entries kept per store (LRU beyond this)
CACHE_CAP = 8

_KERNELS: Dict[str, Any] = {}
_KERNELS_LOCK = threading.Lock()
# live per-store build caches (entry counting for /debug/device) and the
# most recent build's bucket-occupancy summary (the skew histogram an
# operator reads when a join slows down)
_CACHES: "weakref.WeakSet" = weakref.WeakSet()
# guards _CACHES add vs. join_debug's sum — a WeakSet mutated during
# iteration raises, and a first join on a fresh store must not blank the
# /debug/device join block (GC removals are iteration-safe already)
_CACHES_LOCK = threading.Lock()
_LAST_BUILD: Dict[str, Any] = {}
_LAST_BUILD_LOCK = threading.Lock()

# conservative meters-per-degree FLOOR for bucket-envelope expansion:
# deliberately BELOW the true spherical scale (~111195 m/deg; contrast
# geometry.METERS_PER_DEGREE = 111320, which rounds the other way for
# epsilon widening), so dlat/dlon only ever OVER-cover. Never raise it
# past the true scale — an under-covered envelope drops boundary dwithin
# pairs on device AND host alike, since both share the bucket routing.
M_PER_DEG_FLOOR = 111000.0

# dwithin radii past this (10,000 km) decline the device kernel: near the
# antipodal distance (~20,015 km) the haversine's asin amplifies a few
# ulps of f32 error past any fixed epsilon band (asin'(s) = 1/sqrt(1-s²)
# blows up as s -> 1), so the f32 mask stops being a guaranteed superset
# of the f64 predicate. The host path answers such joins exactly; at
# these radii the bucket cover is the whole world anyway, so the kernel
# has no pruning advantage to give up.
DWITHIN_DEVICE_MAX_R_M = 1.0e7


class JoinError(ValueError):
    """Bad join request (unknown predicate, missing radius, non-point
    probe side)."""


@dataclass(frozen=True)
class JoinSpec:
    """Parsed join predicate: ``contains`` (probe point in build
    polygon, boundary inclusive — JTS intersects semantics, matching
    ``geom.predicates.points_in_geometry``) or ``dwithin`` (haversine
    meters between probe and build points)."""

    kind: str
    radius_m: float = 0.0

    @classmethod
    def parse(cls, predicate, radius_m: Optional[float] = None) -> "JoinSpec":
        if isinstance(predicate, JoinSpec):
            return predicate
        p = str(predicate).strip().lower()
        if p == "dwithin" or p.startswith("dwithin("):
            inner = p[len("dwithin"):].strip()
            if inner:
                # anything after "dwithin" must be a complete (...) —
                # a typo like "dwithin500" must fail crisply, not run
                # with the separately-supplied radius
                if not inner.endswith(")"):
                    raise JoinError(
                        f"malformed dwithin predicate: {predicate!r}"
                    )
                radius_m = inner[1:-1]
            if radius_m is None:
                raise JoinError("dwithin join needs a radius: dwithin(<meters>)")
            try:
                radius_m = float(radius_m)
            except (TypeError, ValueError):
                raise JoinError(
                    f"dwithin radius must be a number, got {radius_m!r}"
                ) from None
            if radius_m < 0:
                raise JoinError("dwithin radius must be >= 0")
            return cls("dwithin", radius_m)
        if p == "contains":
            return cls("contains")
        raise JoinError(
            f"unknown join predicate {predicate!r} (contains | dwithin(r))"
        )


def _pow2_at_least(n: int, floor: int) -> int:
    """The executor's pad-bucket rule, single-sourced: build-side
    candidate caps and probe-group pads must bucket exactly like every
    other segment shape or the jit shape model drifts."""
    from geomesa_tpu.parallel.executor import _pow2_at_least as impl

    return impl(n, floor)


def _knobs() -> Tuple[int, int, int, float, int]:
    from geomesa_tpu.utils.config import (
        JOIN_BUCKET_BITS,
        JOIN_CACHE_TTL,
        JOIN_PROBE_CHUNK,
        JOIN_SKEW_THRESHOLD,
        JOIN_SPLIT_DEPTH,
    )

    # None-checked, not falsy-or'd: an explicit 0 is a legitimate
    # setting (split.depth=0 disables adaptive splits) and must be
    # honored — the PR 6 shard-knob rule
    def val(prop, default):
        got = prop.to_int()
        return default if got is None else got

    bits = max(1, val(JOIN_BUCKET_BITS, 3))
    threshold = max(1, val(JOIN_SKEW_THRESHOLD, 128))
    depth = max(0, val(JOIN_SPLIT_DEPTH, 6))
    ttl = JOIN_CACHE_TTL.to_duration_s(600.0)
    chunk = max(1, val(JOIN_PROBE_CHUNK, 2048))
    return bits, threshold, depth, ttl, chunk


# -- grid cells ---------------------------------------------------------------


def _cell_of(x: float, y: float, bits: int) -> Tuple[int, int]:
    n = 1 << bits
    cx = min(n - 1, max(0, int((x + 180.0) / 360.0 * n)))
    cy = min(n - 1, max(0, int((y + 90.0) / 180.0 * n)))
    return cx, cy

def _cell_bounds(bits: int, cx: int, cy: int) -> Tuple[float, float, float, float]:
    w = 360.0 / (1 << bits)
    h = 180.0 / (1 << bits)
    return (-180.0 + cx * w, -90.0 + cy * h, -180.0 + (cx + 1) * w, -90.0 + (cy + 1) * h)


def _cover_cells(bits: int, env: np.ndarray) -> List[Tuple[int, int]]:
    """All (cx, cy) cells at ``bits`` overlapped by one [4] envelope.

    A radius-expanded envelope may cross the antimeridian (lon outside
    [-180, 180]); the overflow WRAPS to the far columns instead of
    clamping — a geofence at lon 179.9 must be routable from a probe at
    -179.9 or dwithin pairs straddling the date line silently vanish.
    Latitude only clamps (no wrap over the poles; near-pole radii widen
    dlon toward the whole-world cover in ``_expand_envs``)."""
    n = 1 << bits
    cy0 = min(n - 1, max(0, int((env[1] + 90.0) / 180.0 * n)))
    cy1 = min(n - 1, max(0, int((env[3] + 90.0) / 180.0 * n)))
    xmin, xmax = float(env[0]), float(env[2])
    if xmax - xmin >= 360.0:
        segs = [(-180.0, 180.0)]
    elif xmin < -180.0:
        segs = [(-180.0, xmax), (xmin + 360.0, 180.0)]
    elif xmax > 180.0:
        segs = [(xmin, 180.0), (-180.0, xmax - 360.0)]
    else:
        segs = [(xmin, xmax)]
    cols = set()
    for sx0, sx1 in segs:
        cx0 = min(n - 1, max(0, int((sx0 + 180.0) / 360.0 * n)))
        cx1 = min(n - 1, max(0, int((sx1 + 180.0) / 360.0 * n)))
        cols.update(range(cx0, cx1 + 1))
    return [(cx, cy) for cx in sorted(cols) for cy in range(cy0, cy1 + 1)]


def _lon_overlaps(exmin: float, exmax: float, cxmin: float, cxmax: float) -> bool:
    """Longitude-interval overlap with antimeridian wrap: an expanded
    envelope running past +-180 overlaps the far-side columns too."""
    if exmax - exmin >= 360.0:
        return True
    if exmin < -180.0:
        segs = ((-180.0, exmax), (exmin + 360.0, 180.0))
    elif exmax > 180.0:
        segs = ((exmin, 180.0), (-180.0, exmax - 360.0))
    else:
        segs = ((exmin, exmax),)
    return any(s0 <= cxmax and s1 >= cxmin for s0, s1 in segs)


def _expand_envs(envs: np.ndarray, spec: JoinSpec) -> np.ndarray:
    """Build geometries' bucket-insertion envelopes, vectorized over an
    [N, 4] array (one numpy pass — a 100k-row dwithin build must not
    pay 100k Python-level calls per cache miss): each envelope widened
    by the predicate radius (latitude-aware for longitude — a 500 m
    radius spans far more lon degrees near the poles than the planner's
    equator-scale conversion suggests) plus the boundary band and the
    curve layer's snap epsilon, so a probe point that matches ALWAYS
    routes to a bucket holding the geometry."""
    envs = np.asarray(envs, dtype=np.float64)
    band = max(snap_epsilon_deg(), PIP_BAND_DEG)
    if spec.kind == "dwithin":
        r_m = spec.radius_m + snap_epsilon_m(spec.radius_m)
        dlat = r_m / M_PER_DEG_FLOOR + band
        lat_reach = np.minimum(
            90.0, np.maximum(np.abs(envs[:, 1]), np.abs(envs[:, 3])) + dlat
        )
        # a radius cap that wraps a pole makes every cos-scaled dlon
        # unsound: two points at the same high latitude but opposite
        # longitudes can sit within r OVER the pole — cover every column
        safe = lat_reach < 90.0 - 1e-9
        dlon = np.where(
            safe,
            r_m
            / (M_PER_DEG_FLOOR
               * np.cos(np.radians(np.where(safe, lat_reach, 0.0))))
            + band,
            360.0,
        )
    else:
        dlat = band
        dlon = band
    out = np.empty_like(envs)
    out[:, 0] = envs[:, 0] - dlon
    out[:, 1] = envs[:, 1] - dlat
    out[:, 2] = envs[:, 2] + dlon
    out[:, 3] = envs[:, 3] + dlat
    return out


# -- build side ---------------------------------------------------------------


def _geometry_edges(g: Geometry) -> Optional[np.ndarray]:
    """[E, 4] f32 edge list for the even-odd ray cast, or None when the
    geometry cannot ride the kernel (device-ineligible build member).

    Multi-member MultiPolygons decline: the even-odd parity of the
    concatenated rings equals the UNION only when members are disjoint,
    and nothing at ingest validates that — a point inside an overlap of
    two members crosses an even total and the kernel would drop a pair
    the host's member-OR semantics keeps. The host path answers those
    builds exactly (single-member MultiPolygons unwrap and ride)."""
    if isinstance(g, Polygon):
        return polygon_edges(g)
    if isinstance(g, MultiPolygon) and len(g.geoms) == 1:
        return polygon_edges(g.geoms[0])
    return None


class JoinBuild:
    """One build side, bucketed and (lazily) HBM-resident.

    Host state: exact f64 geometries/coordinates (the final word on
    boundary pairs and the degradation path), the bucket map, and the
    materialized build columns the join result re-exposes. Device
    state: edge/coordinate arrays plus the candidate matrix, uploaded
    once via ``ensure_device`` and reused across queries until the
    schema generation moves or the TTL expires."""

    def __init__(self, spec: JoinSpec, ft, columns: Dict[str, np.ndarray],
                 fids: np.ndarray, geoms: Optional[List[Optional[Geometry]]],
                 bx: Optional[np.ndarray], by: Optional[np.ndarray]):
        bits, threshold, depth, _ttl, _chunk = _knobs()
        self.spec = spec
        self.ft = ft
        self.columns = columns
        self.fids = fids
        self.geoms = geoms  # contains: Geometry|None per row
        self.bx = bx        # dwithin: f64 coords per row (NaN = null geom)
        self.by = by
        self.base_bits = bits
        self.built_at = time.time()
        # refreshed by every cache hit: the TTL evicts IDLE builds, not
        # hot ones (staleness is impossible — the cache key carries the
        # schema generation, so a write re-keys instead of aging out)
        self.last_used = self.built_at
        self.device_eligible = True
        self.stats: Dict[str, Any] = {}
        self._dev = None  # (edges/bxy, ecnt, cand) device arrays
        self._dev_lock = threading.Lock()

        n = len(fids)
        envs = np.zeros((n, 4), dtype=np.float64)
        self.active = np.zeros(n, dtype=bool)
        if spec.kind == "contains":
            self.edge_lists: List[Optional[np.ndarray]] = []
            for i, g in enumerate(geoms):
                if g is None:
                    self.edge_lists.append(None)
                    continue
                e = _geometry_edges(g)
                self.edge_lists.append(e)
                if e is None:
                    # non-polygonal member: the kernel cannot evaluate it;
                    # the whole join takes the host path (no silent drop)
                    self.device_eligible = False
                envs[i] = g.envelope.as_tuple()
                self.active[i] = True
        else:
            ok = ~(np.isnan(bx) | np.isnan(by))
            self.active = ok
            envs[:, 0] = np.where(ok, bx, 0.0)
            envs[:, 1] = np.where(ok, by, 0.0)
            envs[:, 2] = envs[:, 0]
            envs[:, 3] = envs[:, 1]
        self.envs = _expand_envs(envs, spec) if n else np.zeros((0, 4))

        # -- bucket + adaptive skew split -------------------------------
        buckets: Dict[Tuple[int, int, int], List[int]] = {}
        splits: set = set()
        n_splits = 0
        for i in np.flatnonzero(self.active):
            for cx, cy in _cover_cells(bits, self.envs[i]):
                buckets.setdefault((bits, cx, cy), []).append(int(i))
        work = [c for c, v in buckets.items() if len(v) > threshold]
        while work:
            cell = work.pop()
            b, cx, cy = cell
            if b - bits >= depth or cell not in buckets:
                continue
            entries = buckets.pop(cell)
            if len(entries) <= threshold:
                buckets[cell] = entries
                continue
            splits.add(cell)
            n_splits += 1
            for ccx in (cx * 2, cx * 2 + 1):
                for ccy in (cy * 2, cy * 2 + 1):
                    cb = _cell_bounds(b + 1, ccx, ccy)
                    child = [
                        i for i in entries
                        if _lon_overlaps(self.envs[i][0], self.envs[i][2],
                                         cb[0], cb[2])
                        and self.envs[i][1] <= cb[3] and self.envs[i][3] >= cb[1]
                    ]
                    if child:
                        key = (b + 1, ccx, ccy)
                        buckets[key] = child
                        if len(child) > threshold and (b + 1 - bits) < depth:
                            work.append(key)
        self.buckets = {c: np.asarray(v, dtype=np.int32)
                        for c, v in buckets.items()}
        self.splits = splits
        sizes = [len(v) for v in buckets.values()]
        self.cand_cap = _pow2_at_least(max(sizes, default=1), 8)
        self.n_splits = n_splits
        reg = devstats_metrics()
        if n_splits:
            reg.inc("join.bucket.splits", n_splits)
        reg.set_gauge("join.buckets", len(buckets))
        reg.set_gauge("join.bucket.max_entries", max(sizes, default=0))
        reg.set_gauge(
            "join.bucket.mean_entries",
            float(np.mean(sizes)) if sizes else 0.0,
        )
        hist: Dict[str, int] = {}
        for s in sizes:
            p = 1
            while p < s:
                p *= 2
            hist[f"<={p}"] = hist.get(f"<={p}", 0) + 1
        self.stats = {
            "geometries": int(self.active.sum()),
            "buckets": len(buckets),
            "splits": n_splits,
            "max_bucket": max(sizes, default=0),
            "candidate_cap": self.cand_cap,
            "histogram": dict(sorted(hist.items(), key=lambda kv: int(kv[0][2:]))),
        }
        with _LAST_BUILD_LOCK:
            _LAST_BUILD.clear()
            _LAST_BUILD.update(self.stats)
        # candidate matrix: bucket row -> padded geometry indices
        self.bucket_rows = {c: r for r, c in enumerate(sorted(self.buckets))}
        cand = np.full((max(len(self.buckets), 1), self.cand_cap), -1,
                       dtype=np.int32)
        for c, idxs in self.buckets.items():
            cand[self.bucket_rows[c], : len(idxs)] = idxs
        self.cand = cand

    def leaf_cell(self, x: float, y: float) -> Tuple[int, int, int]:
        b = self.base_bits
        while True:
            cx, cy = _cell_of(x, y, b)
            if (b, cx, cy) in self.splits:
                b += 1
                continue
            return (b, cx, cy)

    def route(self, x: np.ndarray, y: np.ndarray) -> Dict[Tuple[int, int, int], np.ndarray]:
        """Group probe rows by leaf bucket; rows landing in empty cells
        (no candidates) or carrying NaN coordinates drop out — both by
        construction match nothing. Base-cell routing is vectorized;
        only rows whose base cell was skew-split take the per-point
        descent (the split set is small by construction)."""
        idx = np.flatnonzero(~(np.isnan(x) | np.isnan(y)))
        if not len(idx):
            return {}
        n = 1 << self.base_bits
        cx = np.clip(((x[idx] + 180.0) / 360.0 * n).astype(np.int64), 0, n - 1)
        cy = np.clip(((y[idx] + 90.0) / 180.0 * n).astype(np.int64), 0, n - 1)
        key = cx * n + cy
        order = np.argsort(key, kind="stable")
        sidx = idx[order]
        skey = key[order]
        groups: Dict[Tuple[int, int, int], np.ndarray] = {}
        refined: Dict[Tuple[int, int, int], List[int]] = {}
        skx = cx[order]
        sky = cy[order]
        bounds = np.flatnonzero(np.diff(skey)) + 1
        for grp, g0 in zip(np.split(sidx, bounds),
                           np.concatenate([[0], bounds])):
            cell = (self.base_bits, int(skx[g0]), int(sky[g0]))
            if cell in self.splits:
                for i in grp:
                    leaf = self.leaf_cell(float(x[i]), float(y[i]))
                    if leaf in self.buckets:
                        refined.setdefault(leaf, []).append(int(i))
            elif cell in self.buckets:
                groups[cell] = grp.astype(np.int64)
        for c, v in refined.items():
            # never collides with groups: a refined leaf always carries
            # b > base_bits (its base cell is in splits, so the descent
            # takes at least one step), while every groups key is at
            # base_bits exactly
            groups[c] = np.asarray(v, dtype=np.int64)
        return groups

    # -- device residency -------------------------------------------------

    def ensure_device(self, mesh):
        """Upload the build arrays once (edge lists / coordinates and the
        candidate matrix) through the mesh dispatch path; subsequent
        queries reuse the HBM-resident copies. Raises on dispatch faults
        — the caller's degradation path answers from the host state."""
        with self._dev_lock:
            if self._dev is not None:
                return self._dev
            from geomesa_tpu.parallel import mesh as mesh_mod

            if self.spec.kind == "contains":
                g = len(self.edge_lists)
                e_max = max(
                    (len(e) for e in self.edge_lists if e is not None),
                    default=1,
                )
                e_pad = _pow2_at_least(max(e_max, 1), 8)
                edges = np.zeros((max(g, 1), e_pad, 4), dtype=np.float32)
                ecnt = np.zeros(max(g, 1), dtype=np.int32)
                for i, e in enumerate(self.edge_lists):
                    if e is None or not len(e):
                        continue
                    edges[i, : len(e)] = e
                    ecnt[i] = len(e)
                dev = (
                    mesh_mod.replicate(mesh, edges),
                    mesh_mod.replicate(mesh, ecnt),
                    mesh_mod.replicate(mesh, self.cand),
                )
            else:
                bx = np.where(self.active, self.bx, np.nan).astype(np.float32)
                by = np.where(self.active, self.by, np.nan).astype(np.float32)
                dev = (
                    mesh_mod.replicate(mesh, bx),
                    mesh_mod.replicate(mesh, by),
                    mesh_mod.replicate(mesh, self.cand),
                )
            self._dev = dev
            return dev

    def evict_device(self) -> None:
        with self._dev_lock:
            self._dev = None


# -- kernels ------------------------------------------------------------------


def _pip_fn():
    with _KERNELS_LOCK:
        fn = _KERNELS.get("pip")
        if fn is not None:
            return fn

    def run(px, py, cand, edges, ecnt, eps_deg):
        import jax.numpy as jnp

        idx = jnp.maximum(cand, 0)
        e = edges[idx]                      # [C, E, 4]
        cnt = ecnt[idx]                     # [C]
        emask = jnp.arange(e.shape[1])[None, :] < cnt[:, None]  # [C, E]
        x0, y0, x1, y1 = e[..., 0], e[..., 1], e[..., 2], e[..., 3]
        pxb = px[:, None, None]
        pyb = py[:, None, None]
        straddles = ((y0[None] > pyb) != (y1[None] > pyb)) & emask[None]
        denom = jnp.where(y1 - y0 == 0, 1.0, y1 - y0)[None]
        xint = x0[None] + (pyb - y0[None]) * (x1 - x0)[None] / denom
        crossings = jnp.sum(
            (straddles & (xint > pxb)).astype(jnp.int32), axis=2
        )
        inside = (crossings % 2) == 1       # [N, C]
        # min squared point->edge distance (degree space): the boundary
        # band the f32 parity cannot be trusted inside
        abx = (x1 - x0)[None]
        aby = (y1 - y0)[None]
        den = abx * abx + aby * aby
        den = jnp.where(den == 0, 1.0, den)
        t = jnp.clip(
            ((pxb - x0[None]) * abx + (pyb - y0[None]) * aby) / den, 0.0, 1.0
        )
        dx = pxb - (x0[None] + t * abx)
        dy = pyb - (y0[None] + t * aby)
        d2 = jnp.where(emask[None], dx * dx + dy * dy, jnp.inf)
        near = jnp.min(d2, axis=2) <= eps_deg * eps_deg  # [N, C]
        valid = (cand >= 0)[None]
        return (inside & ~near & valid), (near & valid)

    with _KERNELS_LOCK:
        fn = _KERNELS.setdefault("pip", instrumented_jit("join_pip", run))
    return fn


def _dwithin_fn():
    with _KERNELS_LOCK:
        fn = _KERNELS.get("dwithin")
        if fn is not None:
            return fn

    def run(px, py, cand, bx, by, r_m, eps_m):
        import jax.numpy as jnp

        from geomesa_tpu.ops.geometry import haversine_m_f32

        idx = jnp.maximum(cand, 0)
        d = haversine_m_f32(px[:, None], py[:, None], bx[idx][None], by[idx][None])
        valid = (cand >= 0)[None] & ~jnp.isnan(d)
        accept = (d <= r_m - eps_m) & valid
        check = (d > r_m - eps_m) & (d <= r_m + eps_m) & valid
        return accept, check

    with _KERNELS_LOCK:
        fn = _KERNELS.setdefault(
            "dwithin", instrumented_jit("join_dwithin", run)
        )
    return fn


# -- exact host predicates ----------------------------------------------------


def _exact_pairs(build: JoinBuild, gi: int, px: np.ndarray, py: np.ndarray,
                 rows: np.ndarray) -> np.ndarray:
    """Row subset of ``rows`` exactly matching build geometry ``gi``
    (f64; the final word on every boundary pair and the whole host
    path)."""
    if not len(rows):
        return rows
    x = px[rows]
    y = py[rows]
    if build.spec.kind == "contains":
        from geomesa_tpu.geom.predicates import points_in_geometry

        m = points_in_geometry(x, y, build.geoms[gi])
    else:
        from geomesa_tpu.process.geodesy import haversine_m

        m = haversine_m(x, y, build.bx[gi], build.by[gi]) <= build.spec.radius_m
    return rows[m]


def host_join(build: JoinBuild, px: np.ndarray, py: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    """The exact host reference join: same bucket routing as the device
    path, exact f64 predicate per (bucket, candidate). Returns
    (build_idx, probe_idx) sorted (build-major) — identical to the
    device path's canonical pair order."""
    out_b: List[np.ndarray] = []
    out_p: List[np.ndarray] = []
    for cell, rows in build.route(px, py).items():
        deadline.check("join.probe")
        for gi in build.buckets[cell]:
            hit = _exact_pairs(build, int(gi), px, py, rows)
            if len(hit):
                out_b.append(np.full(len(hit), int(gi), dtype=np.int64))
                out_p.append(hit)
    return _canonical_pairs(out_b, out_p)


def _canonical_pairs(out_b: List[np.ndarray], out_p: List[np.ndarray]
                     ) -> Tuple[np.ndarray, np.ndarray]:
    if not out_b:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy()
    bi = np.concatenate(out_b)
    pi = np.concatenate(out_p)
    order = np.lexsort((pi, bi))
    return bi[order], pi[order]


# -- device probe -------------------------------------------------------------


def device_join(build: JoinBuild, mesh, px: np.ndarray, py: np.ndarray,
                stats: Dict[str, Any]) -> Tuple[np.ndarray, np.ndarray]:
    """Stream the probe side through the segment-upload path and
    evaluate the join kernels bucket by bucket. Accepted pairs are
    final; boundary-band pairs take the exact f64 host predicate, so
    the result is identical to ``host_join``."""
    from geomesa_tpu.parallel.executor import join_fetch, join_upload

    _bits, _thr, _depth, _ttl, chunk = _knobs()
    dev = build.ensure_device(mesh)
    extra = (dev[0], dev[1])
    if build.spec.kind == "contains":
        eps = np.float32(max(snap_epsilon_deg(), PIP_BAND_DEG))
        kern = _pip_fn()
    else:
        eps = np.float32(snap_epsilon_m(build.spec.radius_m))
        kern = _dwithin_fn()
    cand_dev = dev[2]
    out_b: List[np.ndarray] = []
    out_p: List[np.ndarray] = []
    verified = 0
    chunks = 0
    for start in range(0, len(px), chunk):
        # per-chunk boundary: injectable, span-wrapped, deadline-paired
        with trace.span("join.probe", chunk=chunks, rows=min(chunk, len(px) - start)):
            deadline.check("join.probe")
            faults.fault_point("join.probe")
            cx = px[start : start + chunk]
            cy = py[start : start + chunk]
            for cell, rows in build.route(cx, cy).items():
                deadline.check("join.probe")
                gx, gy = join_upload(
                    mesh, cx[rows], cy[rows], floor=GROUP_FLOOR
                )
                crow = cand_dev[build.bucket_rows[cell]]
                if build.spec.kind == "contains":
                    accept, check = kern(gx, gy, crow, *extra, eps)
                else:
                    accept, check = kern(
                        gx, gy, crow, *extra,
                        np.float32(build.spec.radius_m), eps,
                    )
                accept = join_fetch(accept)[: len(rows)]
                check = join_fetch(check)[: len(rows)]
                cands = build.buckets[cell]
                for j in range(len(cands)):
                    gi = int(cands[j])
                    hit = rows[accept[:, j]]
                    band = rows[check[:, j]]
                    if len(band):
                        verified += len(band)
                        band = _exact_pairs(build, gi, cx, cy, band)
                    if len(hit) or len(band):
                        both = np.concatenate([hit, band])
                        out_b.append(np.full(len(both), gi, dtype=np.int64))
                        out_p.append(both + start)
        chunks += 1
    stats["chunks"] = chunks
    stats["band_verified"] = verified
    devstats_metrics().inc("join.probe.chunks", chunks)
    return _canonical_pairs(out_b, out_p)


# -- build cache --------------------------------------------------------------


class JoinBuildCache:
    """Per-store TTL'd LRU of JoinBuild structures, keyed by (type name,
    filter, schema generation = index table versions, spec, knobs). A
    generation move (any write/compact) changes the key, so a stale
    build can never answer; the TTL bounds HBM residency of idle
    builds."""

    def __init__(self):
        self._entries: Dict[tuple, JoinBuild] = {}
        self._lock = threading.Lock()
        with _CACHES_LOCK:
            _CACHES.add(self)

    def get(self, key: tuple, ttl_s: float) -> Optional[JoinBuild]:
        reg = devstats_metrics()
        with self._lock:
            self._sweep(ttl_s)
            b = self._entries.pop(key, None)
            if b is not None:
                self._entries[key] = b  # LRU refresh
                b.last_used = time.time()
                reg.inc("join.build.hits")
                return b
        reg.inc("join.build.misses")
        return None

    def _sweep(self, ttl_s: float) -> None:
        """Drop EVERY expired entry, not just a same-key hit: idle
        builds must release their HBM arrays at TTL, or a handful of
        abandoned geofence sets stays device-resident until capacity
        eviction (the 'TTL bounds HBM residency' contract). Called
        under the lock."""
        now = time.time()
        for k in [k for k, b in self._entries.items()
                  if now - b.last_used > ttl_s]:
            self._entries.pop(k).evict_device()

    def put(self, key: tuple, build: JoinBuild) -> None:
        with self._lock:
            # two concurrent misses on one key both build: the displaced
            # loser releases its device arrays like every other removal
            # path, instead of pinning HBM until GC
            old = self._entries.pop(key, None)
            if old is not None and old is not build:
                old.evict_device()
            self._entries[key] = build
            while len(self._entries) > CACHE_CAP:
                self._entries.pop(next(iter(self._entries))).evict_device()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# -- planner ------------------------------------------------------------------


class JoinResult:
    """Joined pairs + both sides' materialized state.

    ``build_idx``/``probe_idx`` are parallel row-index arrays in
    canonical (build-major) order; ``pairs()`` exposes fid tuples and
    ``raw_columns()`` the spatial_join-shaped joined column dict
    (matched probe rows + build columns, suffixed on collision)."""

    def __init__(self, probe, build: JoinBuild, build_idx: np.ndarray,
                 probe_idx: np.ndarray, stats: Dict[str, Any], plan=None):
        self.probe = probe
        self.build = build
        self.build_idx = build_idx
        self.probe_idx = probe_idx
        self.stats = stats
        self.plan = plan

    def __len__(self) -> int:
        return len(self.build_idx)

    @property
    def build_fids(self) -> np.ndarray:
        return self.build.fids[self.build_idx]

    @property
    def probe_fids(self) -> np.ndarray:
        fids = self.probe.columns["__fid__"]
        return np.asarray(fids, dtype=object)[self.probe_idx]

    def pairs(self, limit: Optional[int] = None) -> List[Tuple[str, str]]:
        """Fid pairs in canonical order; ``limit`` slices the index
        arrays BEFORE any fid materialization (an explicit client cap
        must not pay for the pairs it asked to skip)."""
        bi = self.build_idx[:limit] if limit is not None else self.build_idx
        pi = self.probe_idx[:limit] if limit is not None else self.probe_idx
        pfids = np.asarray(self.probe.columns["__fid__"], dtype=object)
        return [
            (str(b), str(p))
            for b, p in zip(self.build.fids[bi], pfids[pi])
        ]

    def raw_columns(self, suffix: str = "_r") -> Dict[str, np.ndarray]:
        pcols = self.probe.columns
        if hasattr(pcols, "materialize"):
            pcols = pcols.materialize()
        cols = {k: v[self.probe_idx] for k, v in pcols.items()}
        for k, v in self.build.columns.items():
            key = (k + suffix) if k in pcols else k
            cols[key] = v[self.build_idx]
        return cols


class JoinPlanner:
    """Build-once / probe-streamed join execution over a datastore.

    The build side queries once per schema generation (the per-store
    ``JoinBuildCache`` keyed by index-table versions — any write or
    compaction moves the key) and stays HBM-resident; the probe side is
    an ordinary store query whose surviving coordinates stream through
    the device kernels, with the host reference join as the degradation
    target for ANY device failure."""

    def __init__(self, store):
        self.store = store

    def join(self, build_name: str, build_query, probe_name: str,
             probe_query, spec: JoinSpec) -> JoinResult:
        import os

        from geomesa_tpu.filter.parser import to_cql
        from geomesa_tpu.parallel import mesh as mesh_mod

        store = self.store
        bits, threshold, depth, ttl, _chunk = _knobs()
        cache = getattr(store, "_join_cache", None)
        if cache is None:
            # dict.setdefault is atomic under the GIL: two concurrent
            # first joins agree on ONE cache (a plain assignment would
            # let the loser's build put() vanish into an orphaned cache
            # that pins its device arrays until GC)
            cache = store.__dict__.setdefault("_join_cache", JoinBuildCache())
        def cache_key() -> tuple:
            # schema_generation covers BOTH local index-table versions
            # (lazy replay moves them) and the store's write counter —
            # the latter is the only signal on coordinators whose rows
            # live on shard workers (ShardedDataStore). The FULL build
            # query identity keys too: a limit/projection/sort/hint
            # changes which rows and columns the build read, and two
            # builds sharing only a filter must never collide
            return (
                build_name, to_cql(build_query.filter),
                build_query.max_features,
                tuple(build_query.properties)
                if build_query.properties is not None else None,
                tuple(build_query.sort_by)
                if build_query.sort_by else None,
                repr(sorted(build_query.hints.items(), key=repr))
                if build_query.hints else None,
                store.schema_generation(build_name), spec.kind,
                round(spec.radius_m, 3), bits, threshold, depth,
            )

        # settle a lazy store's partition replay BEFORE the key is
        # computed (store.query re-runs the hook as a no-op), then
        # capture the key ONCE: a concurrent write landing mid-build
        # moves the generation PAST this key, so a build that read
        # pre-write rows can never answer a post-write join. Re-keying
        # after the query would file that stale build under the
        # post-write generation and serve it for a TTL.
        store._prepare_query(build_name, build_query)
        key = cache_key()
        build = cache.get(key, ttl)
        rebuilt = build is None
        if rebuilt:
            res_b = store.query(build_name, build_query)
            build = self._make_build(res_b, spec)
            cache.put(key, build)

        probe_res = store.query(probe_name, probe_query)
        gname = (
            probe_res.ft.default_geometry.name
            if probe_res.ft.default_geometry is not None else None
        )
        pcols = probe_res.columns
        if gname is None or (gname + "__x") not in pcols:
            raise JoinError(
                f"probe side {probe_name!r} must be a point schema"
            )
        px = np.asarray(pcols[gname + "__x"], dtype=np.float64)
        py = np.asarray(pcols[gname + "__y"], dtype=np.float64)

        stats: Dict[str, Any] = {"build": "rebuild" if rebuilt else "hit"}
        stats.update(build.stats)
        # cache-engagement tally on the join's plan fingerprint
        # (utils/plans.py; one contextvar read when plan telemetry is off)
        plans_mod.note("join.build", "rebuild" if rebuilt else "hit")
        mesh = getattr(store.executor, "mesh", None)
        env = os.environ.get("GEOMESA_JOIN_DEVICE", "auto")
        # kernel eligibility, decomposed so every decline is reason-coded
        # (utils/audit.decision): WHY a join ran host-side is part of its
        # plan-quality record, not something to re-derive from the inputs
        use_device = mesh is not None
        if use_device and not build.device_eligible:
            # e.g. a multi-member MultiPolygon build: concatenated
            # even-odd parity != member union (see _geometry_edges)
            audit_mod.decision(
                "join.kernel", "build_ineligible", build=build_name
            )
            use_device = False
        if use_device and (
            spec.kind == "dwithin" and spec.radius_m > DWITHIN_DEVICE_MAX_R_M
        ):
            audit_mod.decision(
                "join.kernel", "antipodal_radius",
                radius_m=float(spec.radius_m),
            )
            use_device = False
        if use_device and env == "0":
            audit_mod.decision("join.kernel", "env_disabled")
            use_device = False
        if use_device and mesh_mod.device_tripped(
            store.executor, "GEOMESA_JOIN_DEVICE"
        ):
            audit_mod.decision("join.kernel", "device_tripped")
            use_device = False
        if use_device:
            # brownout speculation gate (utils/brownout.py): at the
            # hedge-off ladder level, fresh device build/compile work is
            # capacity the queue needs more — the host reference join
            # answers with identical pairs
            bo = getattr(store, "_brownout", None)
            if bo is not None and not bo.speculation_allowed():
                from geomesa_tpu.utils import brownout as brownout_mod

                if brownout_mod.enabled():
                    audit_mod.decision(
                        "join.kernel", "brownout", level=bo.level
                    )
                    use_device = False
        bi = pi = None
        path = "host-join"
        if use_device:
            try:
                # the device boundary of the build side: upload (or reuse)
                # the HBM-resident structure. Injectable + span-wrapped +
                # deadline-paired; a failure here or in any probe chunk
                # degrades the whole join to the host reference path.
                with trace.span("join.build", type=build_name,
                                cached=not rebuilt):
                    deadline.check("join.build")
                    faults.fault_point("join.build")
                    build.ensure_device(mesh)
                bi, pi = device_join(build, mesh, px, py, stats)
                path = "device-join"
            except Exception as e:  # noqa: BLE001 - device/tunnel failure
                from geomesa_tpu.utils.audit import (
                    QueryTimeout,
                    robustness_metrics,
                )

                if isinstance(e, QueryTimeout):
                    raise  # the query's budget died, not the device
                robustness_metrics().inc("degrade.join_to_host")
                trace.event(
                    "degrade.join_to_host",
                    reason=f"{type(e).__name__}: {e}",
                )
                audit_mod.decision(
                    "degrade", "join_to_host", error=type(e).__name__
                )
                mesh_mod.trip_device(
                    store.executor, "GEOMESA_JOIN_DEVICE", "join", e
                )
                build.evict_device()
                path = "host-join-degraded"
        if bi is None:
            bi, pi = host_join(build, px, py)
        stats["path"] = path
        stats["pairs"] = int(len(bi))
        stats["probed"] = int(len(px))
        devstats_metrics().inc("join.pairs", int(len(bi)))
        return JoinResult(probe_res, build, bi, pi, stats, probe_res.plan)

    @staticmethod
    def _make_build(res_b, spec: JoinSpec) -> JoinBuild:
        cols = res_b.columns
        if hasattr(cols, "materialize"):
            cols = cols.materialize()
        ft = res_b.ft
        geom = ft.default_geometry
        if geom is None:
            raise JoinError(f"build side {ft.name!r} has no geometry")
        fids = np.asarray(cols.get("__fid__", np.empty(0, object)), object)
        if spec.kind == "contains":
            if geom.name not in cols:
                raise JoinError(
                    "contains join needs a polygonal build side "
                    f"({ft.name!r} stores points)"
                )
            geoms = list(cols[geom.name])
            return JoinBuild(spec, ft, cols, fids, geoms, None, None)
        if (geom.name + "__x") not in cols:
            raise JoinError(
                f"dwithin join needs a point build side ({ft.name!r})"
            )
        bx = np.asarray(cols[geom.name + "__x"], dtype=np.float64)
        by = np.asarray(cols[geom.name + "__y"], dtype=np.float64)
        return JoinBuild(spec, ft, cols, fids, None, bx, by)


def _cache_entries_total() -> int:
    with _CACHES_LOCK:
        return sum(len(c) for c in _CACHES)


def join_debug() -> Dict[str, Any]:
    """The ``join`` block of GET /debug/device: build-cache occupancy +
    hit/miss counters, the latest build's bucket skew histogram, and
    the split/pair counters."""
    reg = devstats_metrics()
    counters, gauges, _t, _tt = reg.snapshot()
    with _LAST_BUILD_LOCK:
        last = dict(_LAST_BUILD)
    return {
        "build_cache": {
            "entries": _cache_entries_total(),
            "hits": counters.get("join.build.hits", 0),
            "misses": counters.get("join.build.misses", 0),
        },
        "buckets": {
            "count": gauges.get("join.buckets", 0),
            "max_entries": gauges.get("join.bucket.max_entries", 0),
            "mean_entries": gauges.get("join.bucket.mean_entries", 0.0),
            "splits_total": counters.get("join.bucket.splits", 0),
            "histogram": last.get("histogram", {}),
        },
        "probe": {
            "chunks": counters.get("join.probe.chunks", 0),
            "pairs": counters.get("join.pairs", 0),
        },
    }
