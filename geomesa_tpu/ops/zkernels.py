"""Morton (Z-order) encode/decode as uint32-limb JAX kernels.

The host curve layer (geomesa_tpu.curve.zorder) runs numpy uint64 magic-mask
passes; TPUs emulate int64, so on device the 62-bit Z2 / 63-bit Z3 keys are
carried as two uint32 limbs ``(hi, lo)`` compared lexicographically. This is
the device-side replacement for the reference's sfcurve-zorder bit twiddling
(called from Z2SFC.scala:52 / Z3SFC.scala:62) and for the row-key decode
inside the tserver Z3Iterator (accumulo/iterators/Z3Iterator.scala:42-65).

Bit layouts match the host layer exactly:
  * Z2: x in even positions, y odd; 31 bits/dim -> 62-bit key.
  * Z3: x at bit 3k, y at 3k+1, t at 3k+2; 21 bits/dim -> 63-bit key.

All helpers are shape-polymorphic over leading dims and jit-safe.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

_U32 = jnp.uint32


def _u(x: int) -> jnp.ndarray:
    return jnp.uint32(x)


# ---------------------------------------------------------------------------
# 32-bit spread/compact primitives
# ---------------------------------------------------------------------------

def part1by1_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Spread the low 16 bits of x to even bit positions (uint32)."""
    x = x.astype(_U32) & _u(0x0000FFFF)
    x = (x ^ (x << 8)) & _u(0x00FF00FF)
    x = (x ^ (x << 4)) & _u(0x0F0F0F0F)
    x = (x ^ (x << 2)) & _u(0x33333333)
    x = (x ^ (x << 1)) & _u(0x55555555)
    return x


def compact1by1_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Gather even bit positions of x into the low 16 bits (uint32)."""
    x = x.astype(_U32) & _u(0x55555555)
    x = (x ^ (x >> 1)) & _u(0x33333333)
    x = (x ^ (x >> 2)) & _u(0x0F0F0F0F)
    x = (x ^ (x >> 4)) & _u(0x00FF00FF)
    x = (x ^ (x >> 8)) & _u(0x0000FFFF)
    return x


def part1by2_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Spread the low 10 bits of x to every third bit position (uint32)."""
    x = x.astype(_U32) & _u(0x000003FF)
    x = (x ^ (x << 16)) & _u(0xFF0000FF)
    x = (x ^ (x << 8)) & _u(0x0F00F00F)
    x = (x ^ (x << 4)) & _u(0xC30C30C3)
    x = (x ^ (x << 2)) & _u(0x49249249)
    return x


def compact1by2_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Gather bits 0,3,...,30 of x into the low 11 bits (uint32)."""
    x = x.astype(_U32) & _u(0x49249249)
    x = (x ^ (x >> 2)) & _u(0xC30C30C3)
    x = (x ^ (x >> 4)) & _u(0x0F00F00F)
    x = (x ^ (x >> 8)) & _u(0xFF0000FF)
    x = (x ^ (x >> 16)) & _u(0x000007FF)
    return x


def _shift_left_limbs(hi: jnp.ndarray, lo: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(hi, lo) << k for a static small k (0..31)."""
    if k == 0:
        return hi, lo
    return (hi << k) | (lo >> (32 - k)), lo << k


# ---------------------------------------------------------------------------
# Z2: 31 bits/dim -> 62-bit (hi, lo)
# ---------------------------------------------------------------------------

def _spread2_limbs(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Spread 31-bit x to even positions of a 62-bit (hi, lo) pair."""
    x = x.astype(_U32)
    lo = part1by1_u32(x & _u(0xFFFF))
    hi = part1by1_u32((x >> 16) & _u(0x7FFF))
    return hi, lo


def z2_encode_limbs(xi: jnp.ndarray, yi: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Interleave two <=31-bit int arrays into 62-bit Morton limbs (hi, lo)."""
    xh, xl = _spread2_limbs(xi)
    yh, yl = _spread2_limbs(yi)
    yh, yl = _shift_left_limbs(yh, yl, 1)
    return xh | yh, xl | yl


def _gather2_dim(hi: jnp.ndarray, lo: jnp.ndarray, k: int) -> jnp.ndarray:
    """Extract the dim at even-offset k (0=x, 1=y) from 62-bit limbs."""
    if k:
        low = (lo >> k) | (hi << (32 - k))
        high = hi >> k
    else:
        low, high = lo, hi
    return compact1by1_u32(low) | (compact1by1_u32(high) << 16)


def z2_decode_limbs(hi: jnp.ndarray, lo: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    hi = hi.astype(_U32)
    lo = lo.astype(_U32)
    return _gather2_dim(hi, lo, 0), _gather2_dim(hi, lo, 1)


# ---------------------------------------------------------------------------
# Z3: 21 bits/dim -> 63-bit (hi, lo)
# ---------------------------------------------------------------------------

def _spread3_limbs(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Spread 21-bit x to every third position of a 63-bit (hi, lo) pair.

    21 = 10 + 10 + 1: s(x) = s(a) | s(b) << 30 | c << 60 with each s() a
    28-bit part1by2 spread, recombined across the 32-bit limb boundary.
    """
    x = x.astype(_U32)
    a = part1by2_u32(x & _u(0x3FF))
    b = part1by2_u32((x >> 10) & _u(0x3FF))
    c = (x >> 20) & _u(1)
    lo = a | (b << 30)
    hi = (b >> 2) | (c << 28)
    return hi, lo


def z3_encode_limbs(
    xi: jnp.ndarray, yi: jnp.ndarray, ti: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Interleave three <=21-bit int arrays into 63-bit Morton limbs (hi, lo)."""
    out_hi = jnp.zeros(jnp.shape(xi), dtype=_U32)
    out_lo = jnp.zeros(jnp.shape(xi), dtype=_U32)
    for k, dim in enumerate((xi, yi, ti)):
        h, l = _spread3_limbs(dim)
        h, l = _shift_left_limbs(h, l, k)
        out_hi = out_hi | h
        out_lo = out_lo | l
    return out_hi, out_lo


def _gather3_dim(hi: jnp.ndarray, lo: jnp.ndarray, k: int) -> jnp.ndarray:
    """Extract the dim at stride-3 offset k (0=x, 1=y, 2=t) from 63-bit limbs.

    After v >>= k the dim sits at bits 3i; i in 0..10 come from the low limb,
    i in 11..20 from the high limb at positions 3(i-11)+1.
    """
    if k:
        low = (lo >> k) | (hi << (32 - k))
        high = hi >> k
    else:
        low, high = lo, hi
    lo_bits = compact1by2_u32(low)
    hi_bits = compact1by2_u32(high >> 1) & _u(0x3FF)
    return lo_bits | (hi_bits << 11)


def z3_decode_limbs(
    hi: jnp.ndarray, lo: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    hi = hi.astype(_U32)
    lo = lo.astype(_U32)
    return (
        _gather3_dim(hi, lo, 0),
        _gather3_dim(hi, lo, 1),
        _gather3_dim(hi, lo, 2),
    )


# ---------------------------------------------------------------------------
# Lexicographic limb comparison / range membership
# ---------------------------------------------------------------------------

def limbs_leq(
    a_hi: jnp.ndarray, a_lo: jnp.ndarray, b_hi: jnp.ndarray, b_lo: jnp.ndarray
) -> jnp.ndarray:
    """(a_hi, a_lo) <= (b_hi, b_lo) treating limbs as one unsigned value."""
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def limbs_in_range(
    k_hi: jnp.ndarray,
    k_lo: jnp.ndarray,
    lo_hi: jnp.ndarray,
    lo_lo: jnp.ndarray,
    up_hi: jnp.ndarray,
    up_lo: jnp.ndarray,
) -> jnp.ndarray:
    """Inclusive range membership over any broadcastable limb shapes.

    The device analog of the tserver seeking a key into [lower, upper]
    scan ranges; used to mask sorted key columns against planner output.
    """
    ge = limbs_leq(lo_hi, lo_lo, k_hi, k_lo)
    le = limbs_leq(k_hi, k_lo, up_hi, up_lo)
    return ge & le


def pack_mask_rows(m: jnp.ndarray) -> jnp.ndarray:
    """[..., rows] bool mask -> [..., rows/8] u8 packed bits along the
    LAST axis — THE wire step of every stacked-mask batch kernel
    (parallel/executor: _exact_mask_batch_fn and the per-shard SPMD
    editions). One home so the single-device and shard_map editions can
    never diverge on bit order, and so the row-count contract is stated
    once: the last axis must be a multiple of 8, which DeviceSegment
    guarantees by construction (n_padded divides by 8 * n_devices, so
    both the full table and every per-shard slice pack evenly)."""
    return jnp.packbits(m, axis=-1)


def split_i64_to_limbs(z) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Host-side helper: int64 keys -> (hi, lo) uint32 arrays (numpy in/out)."""
    import numpy as np

    z = np.asarray(z, dtype=np.int64).astype(np.uint64)
    return (z >> np.uint64(32)).astype(np.uint32), (z & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def f64_sort_keys(x) -> "np.ndarray":
    """Host-side: float64 -> uint64 keys whose UNSIGNED order equals the
    float total order (IEEE754 trick: flip all bits of negatives, flip the
    sign bit of non-negatives). -0.0 is collapsed onto +0.0 first so the
    key order matches `==`/`<=` semantics exactly; NaNs map above +inf
    (positive NaN) or below -inf (negative NaN), so they fail any finite
    range test — the behavior the exact device predicate needs for missing
    coordinates. Enables EXACT f64 comparisons on a device whose jax
    config has x64 disabled: compare the keys as two u32 limbs."""
    import numpy as np

    x = np.asarray(x, dtype=np.float64)
    x = np.where(x == 0.0, 0.0, x)
    bits = x.view(np.int64)
    u = bits.view(np.uint64)
    mask = np.where(
        bits < 0, np.uint64(0xFFFFFFFFFFFFFFFF), np.uint64(0x8000000000000000)
    )
    return u ^ mask


def i64_sort_keys(t) -> "np.ndarray":
    """Host-side: int64 -> uint64 keys with matching unsigned order."""
    import numpy as np

    return np.asarray(t, dtype=np.int64).view(np.uint64) ^ np.uint64(1 << 63)


def split_u64_to_limbs(u) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Host-side: uint64 keys -> (hi, lo) uint32 arrays."""
    import numpy as np

    u = np.asarray(u, dtype=np.uint64)
    return (u >> np.uint64(32)).astype(np.uint32), (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def limbs_to_i64(hi, lo):
    """Host-side helper: (hi, lo) uint32 -> int64 keys (numpy in/out)."""
    import numpy as np

    hi = np.asarray(hi, dtype=np.uint64)
    lo = np.asarray(lo, dtype=np.uint64)
    return ((hi << np.uint64(32)) | lo).astype(np.int64)
