"""Device geometry predicates: point-in-polygon and distance masks.

The reference evaluates geometry predicates in JTS on the JVM (CQL
post-filters inside KryoLazyFilterTransformIterator); the device analog is an
even-odd ray cast vectorized over [N] points x [E] polygon edges — the
Pallas/point-in-polygon role called out in SURVEY.md section 7. Results are
float32 and used as *pre*-filters (candidates); exact f64 semantics stay with
the host post-filter.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.geom.base import Geometry, Polygon

# z2 normalization resolution (curve/zorder: 2 dims x 31 bits). The curve
# layer snaps every coordinate to this grid before keys are built, so any
# device predicate evaluated against index-derived coordinates is off by
# at most one cell from the f64 truth.
GRID_BITS = 31
# the planner's meters->degrees conversion constant (filter/ast.DWithin):
# radii must mean the same thing in planner pruning and kernel evaluation
METERS_PER_DEGREE = 111320.0


def snap_epsilon_deg(bits: int = GRID_BITS) -> float:
    """The curve layer's GridSnap quantum in degrees: one normalization
    cell of the wider (longitude) dimension. The largest displacement
    snapping to the z2/z3 grid can introduce per axis — any
    distance-derived pruning or device mask must widen by at least this
    much or boundary rows disagree between the planner's int-domain
    pruning and the kernel's coordinate-domain evaluation."""
    return 360.0 / (1 << bits)


def snap_epsilon_m(radius_m: float = 0.0, bits: int = GRID_BITS) -> float:
    """``snap_epsilon_deg`` in meters (planner conversion scale), plus the
    f32 evaluation slack for a radius of ``radius_m``: float32 carries
    ~7 significant digits, so a great-circle distance near ``radius_m``
    (or near the earth-scale intermediate terms) can round by a few
    meters. The sum is the widening that makes an f32 device dwithin
    mask a guaranteed SUPERSET of the f64 host predicate — the contract
    every device pre-filter in this repo honors."""
    f32_slack = max(16.0, abs(radius_m) * 4e-6)
    return snap_epsilon_deg(bits) * METERS_PER_DEGREE + f32_slack


def polygon_edges(polygon: Polygon) -> np.ndarray:
    """[(x0, y0, x1, y1)] for all rings (shell + holes), f32.

    With the even-odd rule, hole edges flip containment automatically.
    """
    rings = [polygon.shell] + list(getattr(polygon, "holes", []) or [])
    out = []
    for ring in rings:
        coords = np.asarray(ring, dtype=np.float32)
        if len(coords) and not np.array_equal(coords[0], coords[-1]):
            coords = np.vstack([coords, coords[:1]])
        for i in range(len(coords) - 1):
            out.append((coords[i, 0], coords[i, 1], coords[i + 1, 0], coords[i + 1, 1]))
    return np.asarray(out, dtype=np.float32)


def points_in_polygon_f32(
    x: jnp.ndarray, y: jnp.ndarray, edges: jnp.ndarray
) -> jnp.ndarray:
    """Even-odd ray cast: [N] points vs [E, 4] edges -> [N] bool.

    A horizontal ray to +x from each point; crossing parity = containment.
    """
    x0, y0, x1, y1 = edges[:, 0], edges[:, 1], edges[:, 2], edges[:, 3]
    px = x[:, None]
    py = y[:, None]
    # edge straddles the ray's y (half-open to avoid double-count at vertices)
    straddles = (y0[None, :] > py) != (y1[None, :] > py)
    # x coordinate of edge at py
    denom = jnp.where(y1 - y0 == 0, 1.0, y1 - y0)[None, :]
    xint = x0[None, :] + (py - y0[None, :]) * (x1 - x0)[None, :] / denom
    crossings = jnp.sum((straddles & (xint > px)).astype(jnp.int32), axis=1)
    return (crossings % 2) == 1


def haversine_m_f32(
    x: jnp.ndarray, y: jnp.ndarray, cx, cy
) -> jnp.ndarray:
    """Great-circle distance (meters) on device, f32. Broadcasts."""
    r = jnp.float32(6371008.8)
    lon1, lat1 = jnp.radians(x), jnp.radians(y)
    lon2, lat2 = jnp.radians(cx), jnp.radians(cy)
    a = (
        jnp.sin((lat2 - lat1) / 2) ** 2
        + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin((lon2 - lon1) / 2) ** 2
    )
    return 2 * r * jnp.arcsin(jnp.minimum(1.0, jnp.sqrt(a)))


def dwithin_mask_f32(
    x: jnp.ndarray,
    y: jnp.ndarray,
    cx: float,
    cy: float,
    radius_m: float,
    snap_m: float = None,
) -> jnp.ndarray:
    """Haversine distance mask (meters) on device, f32.

    The mask is a candidate PRE-filter, so it must never be stricter
    than the host predicate it screens for: the radius widens by the
    curve layer's GridSnap/normalization epsilon plus the f32 rounding
    slack (``snap_epsilon_m``) so a point exactly on the radius — or
    displaced by one grid cell of index snapping — always survives to
    the exact f64 post-filter. ``snap_m=0.0`` restores the raw
    (parity-unsafe) mask for callers that do their own widening."""
    if snap_m is None:
        snap_m = snap_epsilon_m(radius_m)
    d = haversine_m_f32(x, y, jnp.float32(cx), jnp.float32(cy))
    return d <= jnp.float32(radius_m) + jnp.float32(snap_m)
