"""Device geometry predicates: point-in-polygon and distance masks.

The reference evaluates geometry predicates in JTS on the JVM (CQL
post-filters inside KryoLazyFilterTransformIterator); the device analog is an
even-odd ray cast vectorized over [N] points x [E] polygon edges — the
Pallas/point-in-polygon role called out in SURVEY.md section 7. Results are
float32 and used as *pre*-filters (candidates); exact f64 semantics stay with
the host post-filter.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.geom.base import Geometry, Polygon


def polygon_edges(polygon: Polygon) -> np.ndarray:
    """[(x0, y0, x1, y1)] for all rings (shell + holes), f32.

    With the even-odd rule, hole edges flip containment automatically.
    """
    rings = [polygon.shell] + list(getattr(polygon, "holes", []) or [])
    out = []
    for ring in rings:
        coords = np.asarray(ring, dtype=np.float32)
        if len(coords) and not np.array_equal(coords[0], coords[-1]):
            coords = np.vstack([coords, coords[:1]])
        for i in range(len(coords) - 1):
            out.append((coords[i, 0], coords[i, 1], coords[i + 1, 0], coords[i + 1, 1]))
    return np.asarray(out, dtype=np.float32)


def points_in_polygon_f32(
    x: jnp.ndarray, y: jnp.ndarray, edges: jnp.ndarray
) -> jnp.ndarray:
    """Even-odd ray cast: [N] points vs [E, 4] edges -> [N] bool.

    A horizontal ray to +x from each point; crossing parity = containment.
    """
    x0, y0, x1, y1 = edges[:, 0], edges[:, 1], edges[:, 2], edges[:, 3]
    px = x[:, None]
    py = y[:, None]
    # edge straddles the ray's y (half-open to avoid double-count at vertices)
    straddles = (y0[None, :] > py) != (y1[None, :] > py)
    # x coordinate of edge at py
    denom = jnp.where(y1 - y0 == 0, 1.0, y1 - y0)[None, :]
    xint = x0[None, :] + (py - y0[None, :]) * (x1 - x0)[None, :] / denom
    crossings = jnp.sum((straddles & (xint > px)).astype(jnp.int32), axis=1)
    return (crossings % 2) == 1


def dwithin_mask_f32(
    x: jnp.ndarray, y: jnp.ndarray, cx: float, cy: float, radius_m: float
) -> jnp.ndarray:
    """Haversine distance mask (meters) on device, f32."""
    r = jnp.float32(6371008.8)
    lon1, lat1 = jnp.radians(x), jnp.radians(y)
    lon2, lat2 = jnp.radians(jnp.float32(cx)), jnp.radians(jnp.float32(cy))
    a = (
        jnp.sin((lat2 - lat1) / 2) ** 2
        + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin((lon2 - lon1) / 2) ** 2
    )
    d = 2 * r * jnp.arcsin(jnp.minimum(1.0, jnp.sqrt(a)))
    return d <= radius_m
