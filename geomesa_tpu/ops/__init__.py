"""Device (JAX/XLA) kernels: the TPU analog of the reference's server-side code.

The reference pushes compute to the data with Accumulo iterators / HBase
coprocessors (SURVEY.md section 2.6); here the same role is played by XLA
kernels over HBM-resident columnar blocks:

  * ``zkernels`` — uint32-limb Morton encode/decode (TPU int64 is emulated,
    so 62/63-bit keys are carried as (hi, lo) uint32 pairs).
  * ``filters`` — the Z3Iterator/Z2Iterator analog: vectorized int-domain
    bbox + time-window candidate masks over normalized coordinate columns.
  * ``aggregations`` — density grids / stats / BIN packing push-downs.
"""

from geomesa_tpu.ops.zkernels import (
    z2_encode_limbs,
    z2_decode_limbs,
    z3_encode_limbs,
    z3_decode_limbs,
    limbs_in_range,
)
from geomesa_tpu.ops.filters import (
    pad_boxes,
    pad_windows,
    z2_query_mask,
    z3_query_mask,
    bbox_mask_f32,
)
