"""Vectorized candidate-mask kernels: the Z3Iterator / Z2Iterator analog.

The reference rejects rows inside tablet servers by decoding the row-key z
and testing int-domain bbox + time windows (index-api filters/Z3Filter.scala:
22-58, accumulo iterators/Z3Iterator.scala:42-65). Here the same test runs as
one fused XLA pass over normalized int32 coordinate columns resident in HBM:

    mask[n] = any_k(box_k contains (xi, yi)[n]) & any_w(window_w contains t[n])

Queries pad their box/window lists to pow2 buckets so XLA compiles one kernel
per bucket size, not per query. A True in the mask marks a *candidate*; exact
geometry/CQL semantics are enforced by the post-filter on candidates (the
KryoLazyFilterTransformIterator analog), so padding and int-domain coarseness
never change final result sets.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np


def _next_bucket(n: int, minimum: int = 4) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_boxes(
    boxes: Sequence[Tuple[float, float, float, float]],
    minimum: int = 4,
    dtype=np.int32,
) -> np.ndarray:
    """[(xlo, ylo, xhi, yhi)] boxes -> [K, 4] padded to a pow2 bucket.

    Padding uses inverted boxes (lo > hi) which can never contain a point.
    """
    k = _next_bucket(max(len(boxes), 1), minimum)
    out = np.empty((k, 4), dtype=dtype)
    out[:, 0] = 1
    out[:, 1] = 1
    out[:, 2] = 0
    out[:, 3] = 0
    for i, (xlo, ylo, xhi, yhi) in enumerate(boxes):
        out[i] = (xlo, ylo, xhi, yhi)
    return out


def pad_windows(windows: Sequence[Tuple[int, int, int]], minimum: int = 4) -> np.ndarray:
    """[(bin, lo, hi)] inclusive time windows -> [W, 3] int32/int64 padded.

    Padding uses bin = -1 which never matches a stored (non-negative) bin.
    """
    w = _next_bucket(max(len(windows), 1), minimum)
    # normalized offsets are <= 2^21 so int32 is exact (TPU int64 is emulated)
    out = np.empty((w, 3), dtype=np.int32)
    out[:, 0] = -1
    out[:, 1] = 1
    out[:, 2] = 0
    for i, (b, lo, hi) in enumerate(windows):
        out[i] = (b, lo, hi)
    return out


def spatial_mask(xi: jnp.ndarray, yi: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    """[N] int coords vs [K, 4] int boxes -> [N] bool (any box contains)."""
    xlo, ylo, xhi, yhi = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    x = xi[:, None]
    y = yi[:, None]
    inside = (x >= xlo[None, :]) & (x <= xhi[None, :]) & (y >= ylo[None, :]) & (y <= yhi[None, :])
    return jnp.any(inside, axis=1)


def temporal_mask(bins: jnp.ndarray, offsets: jnp.ndarray, windows: jnp.ndarray) -> jnp.ndarray:
    """[N] (bin, offset) vs [W, 3] (bin, lo, hi) -> [N] bool (any window)."""
    wbin, wlo, whi = windows[:, 0], windows[:, 1], windows[:, 2]
    b = bins.astype(jnp.int32)[:, None]
    t = offsets.astype(jnp.int32)[:, None]
    inside = (b == wbin[None, :]) & (t >= wlo[None, :]) & (t <= whi[None, :])
    return jnp.any(inside, axis=1)


def z3_query_mask(
    xi: jnp.ndarray,
    yi: jnp.ndarray,
    bins: jnp.ndarray,
    offsets: jnp.ndarray,
    valid: jnp.ndarray,
    boxes: jnp.ndarray,
    windows: jnp.ndarray,
) -> jnp.ndarray:
    """The fused Z3Filter.inBounds pass (filters/Z3Filter.scala:22-58)."""
    return valid & spatial_mask(xi, yi, boxes) & temporal_mask(bins, offsets, windows)


def z2_query_mask(
    xi: jnp.ndarray,
    yi: jnp.ndarray,
    valid: jnp.ndarray,
    boxes: jnp.ndarray,
) -> jnp.ndarray:
    """The Z2Filter analog (filters/Z2Filter.scala:18-20)."""
    return valid & spatial_mask(xi, yi, boxes)


def bbox_overlap_mask(
    bxmin: jnp.ndarray,
    bymin: jnp.ndarray,
    bxmax: jnp.ndarray,
    bymax: jnp.ndarray,
    valid: jnp.ndarray,
    boxes: jnp.ndarray,
) -> jnp.ndarray:
    """Per-feature bounding boxes vs [K, 4] query boxes -> any-overlap mask.

    The extent-index (XZ2/XZ3) candidate test: a feature qualifies when its
    bbox intersects any query box (exact geometry intersection is the host
    post-filter's job, mirroring the reference where XZ indices always keep
    the geometry ECQL, XZ2IndexKeySpace.scala:26+).
    """
    qxlo, qylo, qxhi, qyhi = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    overlap = (
        (bxmin[:, None] <= qxhi[None, :])
        & (bxmax[:, None] >= qxlo[None, :])
        & (bymin[:, None] <= qyhi[None, :])
        & (bymax[:, None] >= qylo[None, :])
    )
    return valid & jnp.any(overlap, axis=1)


def bbox_mask_f32(
    x: jnp.ndarray,
    y: jnp.ndarray,
    boxes: jnp.ndarray,
) -> jnp.ndarray:
    """Raw-coordinate bbox mask ([K, 4] f32 boxes); used by aggregations."""
    xlo, ylo, xhi, yhi = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    inside = (
        (x[:, None] >= xlo[None, :])
        & (x[:, None] <= xhi[None, :])
        & (y[:, None] >= ylo[None, :])
        & (y[:, None] <= yhi[None, :])
    )
    return jnp.any(inside, axis=1)


def exact_st_mask(
    x_hi: jnp.ndarray,
    x_lo: jnp.ndarray,
    y_hi: jnp.ndarray,
    y_lo: jnp.ndarray,
    valid: jnp.ndarray,
    box: jnp.ndarray,
    t_hi: jnp.ndarray = None,
    t_lo: jnp.ndarray = None,
    window: jnp.ndarray = None,
) -> jnp.ndarray:
    """EXACT spatio-temporal predicate over f64/i64 sort-key limbs.

    The candidate masks above are conservative (int-normalized domain);
    this one IS the query predicate: coordinates travel as uint32 limb
    pairs of their IEEE754 total-order keys (zkernels.f64_sort_keys), so
    inclusive f64 bbox compares run exactly on devices with x64 disabled.
    ``box`` = u32[8] (xmin/xmax/ymin/ymax key limbs), ``window`` = u32[4]
    (t_lo/t_hi key limbs, inclusive ms). Rows passing this mask need NO
    host post-filter for the primary predicate.
    """
    from geomesa_tpu.ops.zkernels import limbs_in_range

    m = limbs_in_range(x_hi, x_lo, box[0], box[1], box[2], box[3])
    m &= limbs_in_range(y_hi, y_lo, box[4], box[5], box[6], box[7])
    if window is not None:
        m &= limbs_in_range(t_hi, t_lo, window[0], window[1], window[2], window[3])
    return m & valid
