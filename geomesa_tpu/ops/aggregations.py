"""Device aggregation push-downs: density grids and masked reductions.

The reference runs aggregations inside tablet servers so only small partial
results travel to the client (AggregatingScan.scala:22-168, DensityScan.scala:
30-59 with GridSnap, StatsScan, BinAggregatingScan). The TPU analog fuses the
candidate mask with the aggregation in one XLA pass over sharded columns —
features never leave HBM; only the [H, W] grid / scalar reductions do.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from geomesa_tpu.ops.filters import spatial_mask, temporal_mask
from geomesa_tpu.parallel.mesh import DATA_AXIS, gated
from geomesa_tpu.utils.devstats import instrumented_jit


def grid_snap_indices(
    x: jnp.ndarray,
    y: jnp.ndarray,
    env: jnp.ndarray,
    width: int,
    height: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(col, row, in_env) with GridSnap semantics (utils GridSnap.scala:1-120):
    i = floor((v - min) * n / extent), right edge snapped into the last cell.
    ``env`` is a dynamic [4] array (xmin, ymin, xmax, ymax) so new query
    envelopes don't recompile the kernel; only width/height are static."""
    xmin, ymin, xmax, ymax = env[0], env[1], env[2], env[3]
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
    in_env = (x >= xmin) & (x <= xmax) & (y >= ymin) & (y <= ymax)
    col = jnp.clip(col, 0, width - 1)
    row = jnp.clip(row, 0, height - 1)
    return col, row, in_env


def density_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    env: jnp.ndarray,
    width: int,
    height: int,
) -> jnp.ndarray:
    """Masked scatter-add into an [H, W] grid (DensityScan analog)."""
    col, row, in_env = grid_snap_indices(x, y, env, width, height)
    w = jnp.where(mask & in_env, jnp.float32(1.0), jnp.float32(0.0))
    flat = row * width + col
    grid = jnp.zeros(height * width, dtype=jnp.float32)
    grid = grid.at[flat].add(w)
    return grid.reshape(height, width)


_MATMUL_TILE = 16384


def density_kernel_matmul(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    env: jnp.ndarray,
    width: int,
    height: int,
) -> jnp.ndarray:
    """The MXU edition of ``density_kernel`` in PLAIN XLA: the grid as a
    one-hot outer-product matmul, lax.scan'd over static row tiles —

        grid = (W ⊙ onehot_rows)^T @ onehot_cols   per tile, accumulated

    — the same contraction the pallas kernel does
    (pallas_kernels._density_kernel), but lowered by stock XLA, so it
    needs no pallas compile path (the axon remote-compile helper crashed
    on the pallas edition at 8M rows, r5 capture). Scatter-free: on TPU
    the scatter-add edition serializes through ~n dynamic-update-slices,
    while this stays dense matmul work. Identical grid by construction —
    both editions snap through grid_snap_indices."""
    col, row, in_env = grid_snap_indices(x, y, env, width, height)
    wgt = jnp.where(mask & in_env, jnp.float32(1.0), jnp.float32(0.0))
    n = x.shape[0]
    pad = (-n) % _MATMUL_TILE
    if pad:
        col = jnp.pad(col, (0, pad))
        row = jnp.pad(row, (0, pad))
        wgt = jnp.pad(wgt, (0, pad))  # zero weight: padding adds nothing
    nt = (n + pad) // _MATMUL_TILE
    col = col.reshape(nt, _MATMUL_TILE)
    row = row.reshape(nt, _MATMUL_TILE)
    wgt = wgt.reshape(nt, _MATMUL_TILE)
    rows_iota = jax.lax.broadcasted_iota(jnp.int32, (_MATMUL_TILE, height), 1)
    cols_iota = jax.lax.broadcasted_iota(jnp.int32, (_MATMUL_TILE, width), 1)

    def step(acc, rcw):
        r, c, w = rcw
        # bf16 one-hots (0/1 weights are exact in bf16) with f32
        # accumulation: the MXU's native input width, ~2x the f32 path
        r1h = jnp.where(
            r[:, None] == rows_iota, w[:, None], jnp.float32(0.0)
        ).astype(jnp.bfloat16)
        c1h = (c[:, None] == cols_iota).astype(jnp.bfloat16)
        acc = acc + jax.lax.dot_general(
            r1h, c1h,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, None

    # the carry must inherit the inputs' varying-manual-axes type: under
    # shard_map a plain jnp.zeros is unvarying and lax.scan rejects the
    # carry-in/carry-out mismatch — seed it from a (varying) input value
    grid0 = jnp.zeros((height, width), jnp.float32) + wgt[0, 0] * 0.0
    grid, _ = jax.lax.scan(step, grid0, (row, col, wgt))
    return grid


def density_kernel_sort(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    env: jnp.ndarray,
    width: int,
    height: int,
) -> jnp.ndarray:
    """Sort-based edition: flat cell ids sorted once, counts read off as
    differences of searchsorted boundaries — integer-exact, no scatter,
    no per-cell FLOPs (the matmul edition pays 2*H*W FLOPs PER ROW; this
    pays one 32-bit sort + H*W binary searches total). Masked rows sort
    into a discard bucket past the grid."""
    col, row, in_env = grid_snap_indices(x, y, env, width, height)
    hw = height * width
    flat = jnp.where(mask & in_env, row * width + col, jnp.int32(hw))
    s = jnp.sort(flat)
    bounds = jnp.searchsorted(s, jnp.arange(hw + 1, dtype=jnp.int32))
    return jnp.diff(bounds).astype(jnp.float32).reshape(height, width)


def make_sharded_density(mesh, width: int, height: int, mode: str = "xla"):
    """Build jitted shard_map density passes: per-shard fused exact-predicate
    mask + scatter, partial grids psum'd over the row axis (the client-merge
    analog, QueryPlanner.scala:87-92, but on ICI instead of RPC).

    The spatial test runs on raw f32 coords vs raw boxes, the temporal test
    on raw (bin, offset) windows — exact query semantics, not the coarse
    int-domain candidate test, so the grid needs no post-filter.

    mode "pallas"/"pallas_spmd" swaps the per-shard inner pass for the MXU
    one-hot matmul kernel (pallas_kernels.density_grid_pallas) when the
    grid fits its VMEM budget; "xla_matmul" is the same contraction in
    plain XLA (density_kernel_matmul — the pallas-free accelerator
    edition); "xla_sort" counts via one sort + boundary searches
    (density_kernel_sort); "xla" keeps the scatter-add (the CPU shape).
    """
    from geomesa_tpu.ops.filters import bbox_mask_f32
    from geomesa_tpu.ops.pallas_kernels import DENSITY_MAX_DIM, density_grid_pallas

    use_pallas = mode not in ("xla", "xla_matmul", "xla_sort") and (
        width <= DENSITY_MAX_DIM and height <= DENSITY_MAX_DIM
    )

    if use_pallas:
        def step(x, y, bins, offs, valid, boxes, windows, env):
            grid = density_grid_pallas(
                x, y, bins, offs, valid, boxes, windows, env, width, height, True
            )
            return jax.lax.psum(grid, DATA_AXIS)

        def step_no_time(x, y, valid, boxes, env):
            grid = density_grid_pallas(
                x, y, None, None, valid, boxes, None, env, width, height, False
            )
            return jax.lax.psum(grid, DATA_AXIS)
    else:
        kern = {
            "xla_matmul": density_kernel_matmul,
            "xla_sort": density_kernel_sort,
        }.get(mode, density_kernel)

        def step(x, y, bins, offs, valid, boxes, windows, env):
            m = valid & bbox_mask_f32(x, y, boxes) & temporal_mask(bins, offs, windows)
            return jax.lax.psum(kern(x, y, m, env, width, height), DATA_AXIS)

        def step_no_time(x, y, valid, boxes, env):
            m = valid & bbox_mask_f32(x, y, boxes)
            return jax.lax.psum(kern(x, y, m, env, width, height), DATA_AXIS)

    from geomesa_tpu.parallel.mesh import shard_map_fn

    d = P(DATA_AXIS)
    r = P()
    # the psum reduction is a REAL collective: gate both editions so
    # concurrent multi-device executions can never interleave their
    # rendezvous (parallel/mesh.gated — the PR 9 deadlock fence)
    with_time = gated(instrumented_jit("density.time", 
        shard_map_fn(
            step,
            mesh,
            in_specs=(d, d, d, d, d, r, r, r),
            out_specs=r,
            check=not use_pallas,
        )
    ), mesh)
    no_time = gated(instrumented_jit("density.notime", 
        shard_map_fn(
            step_no_time,
            mesh,
            in_specs=(d, d, d, r, r),
            out_specs=r,
            check=not use_pallas,
        )
    ), mesh)
    return with_time, no_time


# --- exact device density: certain grid + host-certified band ---------------
#
# The plain editions bin in f32, so points within f32 error of a grid-cell
# boundary or a query-box edge may land differently than the host's f64
# path (the documented loose-point semantics). The DUAL edition makes the
# device grid EXACTLY host-parity, reusing the banded-polygon idiom
# (parallel/executor._poly_mask_body: device decides the bulk, host
# certifies the ring): rows the device cannot certify in f32 are excluded
# from the device grid and their indices returned for the host to evaluate
# and bin from its f64 block columns.

DENSITY_BAND_CAP = 8192  # per-shard band-candidate budget (32KB i32 d2h)
_BAND_ULPS = 16.0  # margin over the rigorous f32 quantization+rounding bound


def density_band(x, y, env, width, height, boxes):
    """(band, near): ``band`` = rows whose cell assignment or box
    membership could differ between the device's f32 columns/arithmetic
    and the host's f64 originals — f32 quantization of the coordinate
    (<= 0.5 ulp of |x|), f32 rounding of env/box bounds, and the f32
    (x - xmin)/dx evaluation; ``near`` = band rows that additionally pass
    every test with band-widened edges (the candidate set the host must
    certify — band rows far outside every box need no certification).

    Padded boxes are inverted (min > max) and satisfy neither the wide
    nor the strict test; NaN coordinates (null geometries) fail every
    comparison and are never banded."""
    xmin, ymin, xmax, ymax = env[0], env[1], env[2], env[3]
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    eps = jnp.float32(_BAND_ULPS * 2.0 ** -23)
    ex = eps * jnp.maximum(jnp.maximum(jnp.abs(xmin), jnp.abs(xmax)), jnp.abs(x))
    ey = eps * jnp.maximum(jnp.maximum(jnp.abs(ymin), jnp.abs(ymax)), jnp.abs(y))
    tx = (x - xmin) / dx
    ty = (y - ymin) / dy
    ttx = ex / jnp.abs(dx) + eps * jnp.abs(tx)
    tty = ey / jnp.abs(dy) + eps * jnp.abs(ty)
    cell_band = (jnp.abs(tx - jnp.round(tx)) <= ttx) | (
        jnp.abs(ty - jnp.round(ty)) <= tty
    )
    bx0, by0, bx1, by1 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    exk = jnp.maximum(
        ex[:, None], eps * jnp.maximum(jnp.abs(bx0), jnp.abs(bx1))[None, :]
    )
    eyk = jnp.maximum(
        ey[:, None], eps * jnp.maximum(jnp.abs(by0), jnp.abs(by1))[None, :]
    )
    xx, yy = x[:, None], y[:, None]
    in_wide = (
        (xx >= bx0[None, :] - exk) & (xx <= bx1[None, :] + exk)
        & (yy >= by0[None, :] - eyk) & (yy <= by1[None, :] + eyk)
    )
    in_strict = (
        (xx >= bx0[None, :] + exk) & (xx <= bx1[None, :] - exk)
        & (yy >= by0[None, :] + eyk) & (yy <= by1[None, :] - eyk)
    )
    any_strict = jnp.any(in_strict, axis=1)
    box_band = jnp.any(in_wide & ~in_strict, axis=1) & ~any_strict
    band = cell_band | box_band
    near = (
        band
        & jnp.any(in_wide, axis=1)
        & (tx >= -ttx) & (tx <= width + ttx)
        & (ty >= -tty) & (ty <= height + tty)
    )
    return band, near


def make_sharded_density_dual(
    mesh, width: int, height: int, mode: str = "xla",
    band_cap: int = DENSITY_BAND_CAP,
):
    """Dual variants of ``make_sharded_density``: each call returns
    (grid, band_idx, band_count) where the [H, W] grid counts only rows
    the device can certify (mask & ~band), ``band_idx`` is the
    [n_shards * band_cap] packed-array indices of band candidates
    (-1 padding), and ``band_count`` the per-shard true candidate counts
    (count > band_cap means the buffer truncated — the caller must fall
    back to the host path). The executor certifies the band rows against
    the plan's post filter on the f64 host columns and adds their f64
    GridSnap bins, making the final grid exactly host-parity."""
    from geomesa_tpu.ops.filters import bbox_mask_f32
    from geomesa_tpu.ops.pallas_kernels import DENSITY_MAX_DIM, density_grid_pallas

    use_pallas = mode not in ("xla", "xla_matmul", "xla_sort") and (
        width <= DENSITY_MAX_DIM and height <= DENSITY_MAX_DIM
    )
    kern = {
        "xla_matmul": density_kernel_matmul,
        "xla_sort": density_kernel_sort,
    }.get(mode, density_kernel)

    def _band_outputs(cand, local_n):
        cnt = jnp.sum(cand.astype(jnp.int32)).reshape(1)
        idx = jnp.nonzero(cand, size=band_cap, fill_value=local_n)[0].astype(jnp.int32)
        shard = jax.lax.axis_index(DATA_AXIS).astype(jnp.int32)
        gidx = jnp.where(idx < local_n, idx + shard * local_n, jnp.int32(-1))
        return gidx, cnt

    def step(x, y, bins, offs, valid, boxes, windows, env):
        band, near = density_band(x, y, env, width, height, boxes)
        tm = temporal_mask(bins, offs, windows)
        if use_pallas:
            grid = density_grid_pallas(
                x, y, bins, offs, valid & ~band, boxes, windows, env,
                width, height, True,
            )
        else:
            m = valid & bbox_mask_f32(x, y, boxes) & tm
            grid = kern(x, y, m & ~band, env, width, height)
        grid = jax.lax.psum(grid, DATA_AXIS)
        gidx, cnt = _band_outputs(near & valid & tm, x.shape[0])
        return grid, gidx, cnt

    def step_no_time(x, y, valid, boxes, env):
        band, near = density_band(x, y, env, width, height, boxes)
        if use_pallas:
            grid = density_grid_pallas(
                x, y, None, None, valid & ~band, boxes, None, env,
                width, height, False,
            )
        else:
            m = valid & bbox_mask_f32(x, y, boxes)
            grid = kern(x, y, m & ~band, env, width, height)
        grid = jax.lax.psum(grid, DATA_AXIS)
        gidx, cnt = _band_outputs(near & valid, x.shape[0])
        return grid, gidx, cnt

    from geomesa_tpu.parallel.mesh import shard_map_fn

    d = P(DATA_AXIS)
    r = P()
    # psum-bearing like the plain editions: same rendezvous fence
    with_time = gated(instrumented_jit("density_dual.time", 
        shard_map_fn(
            step,
            mesh,
            in_specs=(d, d, d, d, d, r, r, r),
            out_specs=(r, d, d),
            check=not use_pallas,
        )
    ), mesh)
    no_time = gated(instrumented_jit("density_dual.notime", 
        shard_map_fn(
            step_no_time,
            mesh,
            in_specs=(d, d, d, r, r),
            out_specs=(r, d, d),
            check=not use_pallas,
        )
    ), mesh)
    return with_time, no_time


# --- aggregate pyramid build reduction ---------------------------------------
#
# The GeoBlocks-style pyramid (ops/pyramid.py) pre-aggregates every row
# into a coarse z2 cell grid so hot polygon/bbox aggregations answer from
# interior partial sums. The build reduction runs straight off the
# HBM-resident segment mirrors: the z2 segments already hold each row's
# EXACT integer grid coordinates (seg.xi / seg.yi, decoded from the index
# keys), so the device bins by integer shifts — bit-identical to the host
# build that decodes the same keys, no f32 coordinate rounding anywhere.


def make_pyramid_counts(mesh, bits: int, src_bits: int = 31):
    """Jitted shard_map pyramid-count pass: (xi, yi, mask) -> [H, W] i32
    per-cell row counts, psum'd over the data axis. ``mask`` excludes
    tombstoned and null-geometry rows (their lenient-encoded keys would
    otherwise count in cell 0). Counting uses the sort + boundary-search
    idiom (integer-exact, scatter-free — the density_kernel_sort shape)."""
    n = 1 << bits
    shift = src_bits - bits

    def step(xi, yi, mask):
        cx = jax.lax.shift_right_logical(xi, shift)
        cy = jax.lax.shift_right_logical(yi, shift)
        flat = jnp.where(mask, cy * n + cx, jnp.int32(n * n))
        s = jnp.sort(flat)
        bounds = jnp.searchsorted(s, jnp.arange(n * n + 1, dtype=jnp.int32))
        grid = jnp.diff(bounds).astype(jnp.int32).reshape(n, n)
        return jax.lax.psum(grid, DATA_AXIS)

    from geomesa_tpu.parallel.mesh import shard_map_fn

    d = P(DATA_AXIS)
    return gated(instrumented_jit(
        "agg.pyramid",
        shard_map_fn(step, mesh, in_specs=(d, d, d), out_specs=P()),
    ), mesh)


# the host reference implementation lives in geomesa_tpu.index.aggregators
# (pure numpy, so the host-only datastore path has no jax dependency)
