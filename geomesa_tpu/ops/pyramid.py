"""HBM-resident aggregate pyramid cache: sub-millisecond hot aggregations.

The GeoBlocks idea ("GeoBlocks: A Query-Cache Accelerated Data Structure
for Spatial Aggregation over Polygons", PAPERS.md) applied to this repo's
layout: repeated dashboard aggregations (count / stats / density over a
polygon or bbox) stop re-sweeping every candidate segment and instead
answer from hierarchical pre-aggregated blocks, rescanning only the
query's boundary ring.

Structure
---------
An ``AggPyramid`` per feature type is a small stack of z2-gridded levels.
The finest level is a ``2^bits x 2^bits`` grid over the world
(``geomesa.agg.cell.bits``); each level above halves the resolution
(``geomesa.agg.levels``). Cells are COARSENED Z2 CELLS: a row's cell is
its z2 index key's integer grid coordinate shifted down — exact integer
arithmetic shared by the device build kernel (ops/aggregations.
make_pyramid_counts over the HBM-resident segment mirrors), the host
build (z2_decode of the same keys), and the per-query classification, so
all three agree bit-for-bit. Per cell the pyramid holds the row count
(always) and, lazily per consumed column, sum/min/max/non-null-count
(``AggPyramid.ensure_columns``). The finest count grid doubles as the
coarse density grid of the type (``/debug/device`` ``agg`` block).

Exactness (the parity contract)
-------------------------------
``classify`` splits a query's geometry set into INTERIOR cells (every row
binned there provably satisfies the exact f64 predicate), BOUNDARY cells,
and outside cells (no row there can match). Two mechanisms, both
conservative-only:

* rectangles use monotonicity: ``normalize`` (curve/normalized.py) is
  monotone in the coordinate, so cells strictly between the cells of the
  query's own normalized bounds contain only rows strictly inside the
  box — no epsilon, exact by construction;
* polygons use a hierarchical descent with widened cell rectangles
  (``_EPS_DEG`` dominates every f64 rounding in the bin arithmetic by
  ~3 orders of magnitude): a cell whose widened rect no polygon edge
  touches is wholly inside or outside by one center test; touched cells
  recurse to the next finer level and bottom out as boundary.

Interior cells answer from partial sums (exact sums, never estimates);
boundary cells fall through to the exact segment scan — each boundary
cell is ONE contiguous z2 key range, so the fallthrough seeks exactly
the boundary ring and evaluates the plan's own post-filter on those
rows. Fused, a hot polygon aggregation touches only its boundary ring.

Caching and invalidation
------------------------
Pyramids (and the density-grid query memo) live in a per-store
``AggCache`` — the PR 7 ``JoinBuildCache`` pattern: TTL'd LRU keyed by
``(kind, type, schema generation, knobs)``, byte-bounded
(``geomesa.agg.cache.bytes``), device arrays evicted with their entry so
idle pyramids release HBM at TTL. Any write / compact / delete /
delete_schema — including one routed through a ``ShardedDataStore``
worker — bumps the per-type write generation (``_note_write``), which
both re-keys the cache AND drops the type's entries eagerly.

Failure envelope
----------------
``agg.build`` is a named fault point paired with a span and a deadline
check; a build failure degrades the aggregation to the uncached exact
scan path with identical answers (parity under faults covers
aggregations-from-cache; ``scripts/chaos_smoke.sh`` soaks it).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.curve.zorder import z2_decode, z2_encode
from geomesa_tpu.geom.base import Geometry, MultiPolygon, Polygon
from geomesa_tpu.utils import deadline, faults, trace
from geomesa_tpu.utils.devstats import devstats_metrics

# the z2 curve's per-dimension resolution (curve/sfc.Z2SFC default);
# pyramid cells are these integer grid coordinates shifted down
Z2_BITS = 31

# conservative widening (degrees) for polygon cell-rectangle tests: the
# f64 bin arithmetic (normalize + the cell-bound reconstruction here) is
# exact to ~1e-12 deg at world scale; 1e-9 dominates it by 3 orders of
# magnitude while adding ~0.1 mm of area per cell edge. Only ever moves
# borderline cells from interior to boundary — never the unsafe way.
_EPS_DEG = 1e-9

# cell classification codes (uint8 grid)
OUTSIDE, INTERIOR, BOUNDARY = 0, 1, 2

# per-pyramid classification memo (the GeoBlocks "query cache"): a hot
# repeated polygon re-uses its interior sums + boundary ring without
# re-classifying; bounded LRU
CLASSIFY_MEMO_CAP = 64

# per-level-classification chunk so the [cells x edges] overlap test
# stays memory-bounded on huge covers
_CLASSIFY_CHUNK = 1024

# live per-store caches, for /debug/device entry/byte sums (join.py's
# _CACHES posture, including the lock-vs-iteration rule)
_CACHES: "weakref.WeakSet" = weakref.WeakSet()
_CACHES_LOCK = threading.Lock()
_LAST_BUILD: Dict[str, Any] = {}
_LAST_BUILD_LOCK = threading.Lock()


class AggError(ValueError):
    """Bad aggregate request (unknown column, non-numeric column)."""


def agg_enabled() -> bool:
    """The cache's operational escape hatch (geomesa.agg.enabled): off
    routes every aggregation through the ordinary uncached paths —
    identical answers by the parity contract, just no pyramid."""
    from geomesa_tpu.utils.config import AGG_ENABLED

    got = AGG_ENABLED.to_bool()
    return True if got is None else got


def agg_knobs() -> Tuple[int, int, float, int]:
    """(cell_bits, levels, ttl_s, cache_cap_bytes) — resolved fresh per
    call (config values may change under tests). None-checked, not
    falsy-or'd (the PR 6 shard-knob rule)."""
    from geomesa_tpu.utils.config import (
        AGG_CACHE_BYTES,
        AGG_CACHE_TTL,
        AGG_CELL_BITS,
        AGG_LEVELS,
    )

    def val(prop, default):
        got = prop.to_int()
        return default if got is None else got

    bits = min(12, max(2, val(AGG_CELL_BITS, 8)))
    levels = min(bits - 1, max(1, val(AGG_LEVELS, 3)))
    ttl = AGG_CACHE_TTL.to_duration_s(600.0)
    cap = AGG_CACHE_BYTES.to_bytes()
    if cap is None:
        cap = 64 << 20
    return bits, levels, ttl, cap


def could_have_interior(geoms: List[Geometry], bits: int) -> bool:
    """Cheap PRE-BUILD gate for the cost model: can any geometry's
    envelope cover at least one interior cell at the finest level? A
    geometry spanning fewer than 3 cells in either axis has rim-only
    coverage — no interior cell is possible, every candidate row is
    boundary, and ``pyramid_worthwhile`` would decline AFTER paying the
    full O(table) build. Declining here skips the build entirely
    (conservative the cheap way: under-declining only loses caching for
    one query shape, never correctness)."""
    n = 1 << bits
    cw, ch = 360.0 / n, 180.0 / n
    for g in geoms:
        env = g.envelope
        if (env.xmax - env.xmin) >= 3.0 * cw and (env.ymax - env.ymin) >= 3.0 * ch:
            return True
    return False


# -- build --------------------------------------------------------------------


def host_counts(table, ft, bits: int) -> np.ndarray:
    """[H, W] int64 per-cell row counts from the host index table: the
    exact reference the device kernel must match (same key decode, same
    integer shifts, same null-geometry exclusion)."""
    n = 1 << bits
    shift = Z2_BITS - bits
    geom = ft.default_geometry.name
    grid = np.zeros(n * n, dtype=np.int64)
    for b, rows in table.scan_all():
        if not len(rows):
            continue
        xi, yi = z2_decode(b.key[rows])
        # null geometries encode leniently (clipped keys): they can never
        # match a spatial predicate, so they must never count in a cell
        x = np.asarray(b.gather(geom + "__x", rows), dtype=np.float64)
        y = np.asarray(b.gather(geom + "__y", rows), dtype=np.float64)
        ok = np.isfinite(x) & np.isfinite(y)
        flat = ((yi >> shift) * n + (xi >> shift))[ok]
        grid += np.bincount(flat, minlength=n * n)
    return grid.reshape(n, n)


def build_pyramid(table, ft, executor=None) -> "AggPyramid":
    """Materialize one type's pyramid — the ``agg.build`` boundary:
    injectable, span-wrapped, deadline-paired. The device reduction runs
    off the existing segment mirrors when the executor carries them
    (``TpuScanExecutor.pyramid_counts``); the host build is the
    bit-identical fallback. Raises on injected/device faults — the
    caller's degradation path answers from the uncached exact scan."""
    bits, levels, _ttl, _cap = agg_knobs()
    reg = devstats_metrics()
    t0 = time.perf_counter()
    with trace.span("agg.build", type=ft.name, bits=bits, levels=levels):
        deadline.check("agg.build")
        faults.fault_point("agg.build")
        counts0 = None
        pyramid_counts = getattr(executor, "pyramid_counts", None)
        if pyramid_counts is not None:
            counts0 = pyramid_counts(table, bits)
        if counts0 is None:
            counts0 = host_counts(table, ft, bits)
        counts = [np.asarray(counts0, dtype=np.int64)]
        for _ in range(1, levels):
            g = counts[-1]
            if g.shape[0] < 2:
                break
            counts.append(
                g.reshape(g.shape[0] // 2, 2, g.shape[1] // 2, 2).sum(axis=(1, 3))
            )
        pyr = AggPyramid(table.index.sfc(ft), ft, counts)
        mesh = getattr(executor, "mesh", None)
        if mesh is not None:
            pyr.ensure_device(mesh)
    reg.inc("agg.cache.builds")
    reg.update_timer("agg.build", time.perf_counter() - t0)
    with _LAST_BUILD_LOCK:
        _LAST_BUILD.clear()
        _LAST_BUILD.update(pyr.stats)
    return pyr


class AggPyramid:
    """One type's aggregate pyramid: the stack of per-cell count grids
    (``counts[0]`` finest -> coarsest), lazily-built per-column
    sum/min/max/count grids, the per-query classification memo, and the
    HBM-resident device copies."""

    def __init__(self, sfc, ft, counts: List[np.ndarray]):
        self.sfc = sfc
        self.geom = ft.default_geometry.name
        self.counts = counts
        self.bits = int(counts[0].shape[0]).bit_length() - 1
        self.levels = len(counts)
        self.total_rows = int(counts[0].sum())
        self.built_at = time.time()
        self.last_used = self.built_at
        # col -> {"sum","min","max","count"} finest-level grids
        self.col_grids: Dict[str, Dict[str, np.ndarray]] = {}
        self._queries: Dict[Any, tuple] = {}  # classification memo (LRU)
        self._lock = threading.Lock()
        self._dev: Optional[list] = None
        self._dev_lock = threading.Lock()
        self.stats = {
            "type": ft.name,
            "bits": self.bits,
            "levels": self.levels,
            "rows": self.total_rows,
            "cells": int(counts[0].size),
            "occupied": int((counts[0] > 0).sum()),
        }
        reg = devstats_metrics()
        reg.set_gauge("agg.pyramid.cells", int(counts[0].size))
        reg.set_gauge("agg.pyramid.rows", self.total_rows)

    @property
    def nbytes(self) -> int:
        n = sum(g.nbytes for g in self.counts)
        # snapshot under the lock: ensure_columns inserts concurrently
        # (byte-accounting from another query's cache put must not hit a
        # dict-changed-size-during-iteration)
        with self._lock:
            grids_list = list(self.col_grids.values())
        for grids in grids_list:
            n += sum(g.nbytes for g in grids.values())
        return n

    # -- device residency --------------------------------------------------

    def ensure_device(self, mesh):
        """Replicate the level stack into HBM (once); the device copies
        are the cache's resident acceleration structure and are evicted
        with the entry (TTL / capacity / invalidation). Today's query
        answers reduce the HOST grids (interior sums are tiny numpy
        reductions — a device round-trip would cost more than it saves);
        the resident copies exist for the device-side consumers the
        ROADMAP follow-ups name (density grids coarsened on device,
        fused pyramid+scan kernels), and their footprint is bounded by
        geomesa.agg.cache.bytes like everything else in the entry."""
        with self._dev_lock:
            if self._dev is None:
                from geomesa_tpu.parallel import mesh as mesh_mod

                self._dev = [
                    mesh_mod.replicate(mesh, g.astype(np.int32))
                    for g in self.counts
                ]
            return self._dev

    def evict_device(self) -> None:
        with self._dev_lock:
            self._dev = None

    # -- classification ----------------------------------------------------

    def _norm_cell(self, v: float, axis: str, bits: int) -> int:
        """The coarsened cell of one query-bound coordinate, through the
        SAME normalize the index keys used — monotone, so strict
        between-ness in cell space proves strict between-ness in
        coordinate space (no epsilon)."""
        dim = self.sfc.lon if axis == "x" else self.sfc.lat
        n = int(dim.normalize(np.asarray([v], dtype=np.float64))[0])
        n = min(max(n, 0), dim.max_index)
        return n >> (Z2_BITS - bits)

    def _cell_rects(self, bits: int, cells: np.ndarray) -> np.ndarray:
        """[K, 4] widened degree-space rectangles of cells at ``bits``."""
        s = Z2_BITS - bits
        lon, lat = self.sfc.lon, self.sfc.lat
        sx = (lon.max - lon.min) / lon.bins
        sy = (lat.max - lat.min) / lat.bins
        cx = cells[:, 0].astype(np.int64)
        cy = cells[:, 1].astype(np.int64)
        out = np.empty((len(cells), 4), dtype=np.float64)
        out[:, 0] = lon.min + (cx << s) * sx - _EPS_DEG
        out[:, 1] = lat.min + (cy << s) * sy - _EPS_DEG
        out[:, 2] = lon.min + ((cx + 1) << s) * sx + _EPS_DEG
        out[:, 3] = lat.min + ((cy + 1) << s) * sy + _EPS_DEG
        return out

    def classify(self, geoms: List[Geometry], memo_key=None) -> tuple:
        """(interior_rows, boundary_rows, candidate_rows, boundary_cells,
        interior_mask) for a query's geometry set. ``boundary_cells`` is
        [K, 2] (cx, cy) at the finest level; ``interior_mask`` is the
        finest-level bool grid the column aggregates reduce under.
        Memoized per ``memo_key`` (normally the filter's CQL text) — the
        hot-query path re-uses its ring."""
        if memo_key is not None:
            with self._lock:
                got = self._queries.pop(memo_key, None)
                if got is not None:
                    self._queries[memo_key] = got  # LRU refresh
                    return got
        n0 = 1 << self.bits
        cls = np.zeros((n0, n0), dtype=np.uint8)
        for g in self._flatten(geoms):
            if getattr(g, "is_rectangle", lambda: False)():
                self._paint_rect(cls, g.envelope)
            elif isinstance(g, Polygon):
                self._paint_polygon(cls, g)
            else:
                # area-free geometries (lines, points): no cell can be
                # interior; the envelope cover is all boundary
                self._paint_cover_boundary(cls, g.envelope)
        c0 = self.counts[0]
        interior_mask = cls == INTERIOR
        interior_rows = int(c0[interior_mask].sum())
        boundary_rows = int(c0[cls == BOUNDARY].sum())
        cand = interior_rows + boundary_rows
        by, bx = np.nonzero(cls == BOUNDARY)
        boundary_cells = np.stack([bx, by], axis=1).astype(np.int64)
        # drop EMPTY boundary cells: zero rows means zero scan ranges
        occ = c0[by, bx] > 0
        boundary_cells = boundary_cells[occ]
        got = (interior_rows, boundary_rows, cand, boundary_cells, interior_mask)
        if memo_key is not None:
            with self._lock:
                self._queries[memo_key] = got
                while len(self._queries) > CLASSIFY_MEMO_CAP:
                    self._queries.pop(next(iter(self._queries)))
        return got

    @staticmethod
    def _flatten(geoms: List[Geometry]) -> List[Geometry]:
        out: List[Geometry] = []
        for g in geoms:
            if isinstance(g, MultiPolygon):
                out.extend(g.geoms)
            else:
                out.append(g)
        return out

    def _paint_rect(self, cls: np.ndarray, env) -> None:
        """Monotone-exact rectangle painting: rim cells of the box's own
        normalized-bound cells are boundary, strictly-inside cells are
        interior. Interior paint is unconditional (an interior cell of
        ANY geometry needs no exact check); boundary never downgrades
        another geometry's interior."""
        c0 = self._norm_cell(env.xmin, "x", self.bits)
        c1 = self._norm_cell(env.xmax, "x", self.bits)
        r0 = self._norm_cell(env.ymin, "y", self.bits)
        r1 = self._norm_cell(env.ymax, "y", self.bits)
        sub = cls[r0 : r1 + 1, c0 : c1 + 1]
        sub[sub == OUTSIDE] = BOUNDARY
        if r1 - r0 >= 2 and c1 - c0 >= 2:
            cls[r0 + 1 : r1, c0 + 1 : c1] = INTERIOR

    def _paint_cover_boundary(self, cls: np.ndarray, env) -> None:
        c0 = self._norm_cell(env.xmin, "x", self.bits)
        c1 = self._norm_cell(env.xmax, "x", self.bits)
        r0 = self._norm_cell(env.ymin, "y", self.bits)
        r1 = self._norm_cell(env.ymax, "y", self.bits)
        sub = cls[r0 : r1 + 1, c0 : c1 + 1]
        sub[sub == OUTSIDE] = BOUNDARY

    def _paint_polygon(self, cls: np.ndarray, poly: Polygon) -> None:
        """Hierarchical descent (the pyramid's cost model in action):
        classify the envelope cover at the coarsest level; cells no edge
        touches resolve wholly by one center test; touched cells recurse
        and bottom out as finest-level boundary cells."""
        from geomesa_tpu.geom.predicates import points_in_polygon

        rings = [np.asarray(poly.shell, dtype=np.float64)] + [
            np.asarray(h, dtype=np.float64) for h in (poly.holes or [])
        ]
        edges = np.concatenate(
            [
                np.concatenate([r[:-1], r[1:]], axis=1)
                for r in rings
                if len(r) >= 2
            ]
        )  # [E, 4] (x0, y0, x1, y1)
        env = poly.envelope
        bits_c = self.bits - (self.levels - 1)
        c0 = self._norm_cell(env.xmin, "x", bits_c)
        c1 = self._norm_cell(env.xmax, "x", bits_c)
        r0 = self._norm_cell(env.ymin, "y", bits_c)
        r1 = self._norm_cell(env.ymax, "y", bits_c)
        gx, gy = np.meshgrid(
            np.arange(c0, c1 + 1, dtype=np.int64),
            np.arange(r0, r1 + 1, dtype=np.int64),
        )
        cells = np.stack([gx.ravel(), gy.ravel()], axis=1)
        bits_l = bits_c
        while len(cells):
            rects = self._cell_rects(bits_l, cells)
            amb = np.zeros(len(cells), dtype=bool)
            for s0 in range(0, len(cells), _CLASSIFY_CHUNK):
                sl = slice(s0, s0 + _CLASSIFY_CHUNK)
                amb[sl] = _edges_overlap_rects(edges, rects[sl])
            clear = ~amb
            if clear.any():
                cx_mid = (rects[clear, 0] + rects[clear, 2]) * 0.5
                cy_mid = (rects[clear, 1] + rects[clear, 3]) * 0.5
                inside = points_in_polygon(cx_mid, cy_mid, poly)
                shift = self.bits - bits_l
                for (cx, cy) in cells[clear][inside]:
                    cls[
                        cy << shift : (cy + 1) << shift,
                        cx << shift : (cx + 1) << shift,
                    ] = INTERIOR
            cells = cells[amb]
            if bits_l == self.bits:
                keep = cls[cells[:, 1], cells[:, 0]] != INTERIOR
                cls[cells[keep, 1], cells[keep, 0]] = BOUNDARY
                break
            # recurse: 4 children per ambiguous cell at the next level
            cx = cells[:, 0] * 2
            cy = cells[:, 1] * 2
            cells = np.stack(
                [
                    np.stack([cx + dx, cy + dy], axis=1)
                    for dx in (0, 1)
                    for dy in (0, 1)
                ],
                axis=0,
            ).reshape(-1, 2)
            bits_l += 1

    # -- boundary ring -> scan ranges --------------------------------------

    def cell_ranges(self, cells: np.ndarray):
        """Boundary cells -> z2 key ranges (each pyramid cell is one
        contiguous z2 span; z-adjacent cells merge). Returns a RangeSet
        the ordinary IndexTable.scan seeks with."""
        from geomesa_tpu.index.keyspace import RangeSet

        if not len(cells):
            return RangeSet(
                np.empty(0, np.int64), np.empty(0, np.int64),
                np.empty(0, np.int64), np.empty(0, bool),
            )
        s = Z2_BITS - self.bits
        z = np.sort(
            z2_encode(cells[:, 0] << s, cells[:, 1] << s).astype(np.int64)
        )
        span = np.int64(1) << np.int64(2 * s)
        gaps = np.flatnonzero(np.diff(z) != span)
        starts = np.concatenate([[0], gaps + 1])
        ends = np.concatenate([gaps, [len(z) - 1]])
        lower = z[starts]
        upper = z[ends] + span - 1
        return RangeSet(
            np.zeros(len(lower), dtype=np.int64), lower, upper,
            np.zeros(len(lower), dtype=bool),
        )

    # -- per-column aggregate grids ----------------------------------------

    def ensure_columns(self, table, ft, cols: List[str]) -> None:
        """Lazily build sum/min/max/count grids for ``cols`` (one table
        pass for all missing columns). Integer-backed columns (ints,
        dates) accumulate in int64 — exact; floats in f64. An O(table)
        build like the count build, so it runs under the same
        ``agg.build`` envelope: injectable, span-wrapped, and
        deadline-checked per block (the caller degrades a failure to the
        uncached exact scan; a QueryTimeout propagates crisply)."""
        with self._lock:
            missing = [c for c in cols if c not in self.col_grids]
        if not missing:
            return
        with trace.span("agg.build", type=ft.name, columns=len(missing)):
            deadline.check("agg.build")
            faults.fault_point("agg.build")
            self._build_columns(table, ft, missing)

    def _build_columns(self, table, ft, missing: List[str]) -> None:
        n = 1 << self.bits
        shift = Z2_BITS - self.bits
        geom = self.geom
        dtypes = {c: _sum_dtype(ft, c) for c in missing}
        acc = {
            c: {
                "sum": np.zeros(n * n, dtype=dtypes[c]),
                "min": np.full(n * n, np.inf),
                "max": np.full(n * n, -np.inf),
                "count": np.zeros(n * n, dtype=np.int64),
            }
            for c in missing
        }
        for b, rows in table.scan_all():
            deadline.check("agg.build")
            if not len(rows):
                continue
            xi, yi = z2_decode(b.key[rows])
            x = np.asarray(b.gather(geom + "__x", rows), dtype=np.float64)
            y = np.asarray(b.gather(geom + "__y", rows), dtype=np.float64)
            ok = np.isfinite(x) & np.isfinite(y)
            flat = (yi >> shift) * n + (xi >> shift)
            for c in missing:
                v = b.gather(c, rows)
                # a missing __null companion gathers as zeros (blocks.py)
                nulls = b.gather(c + "__null", rows)
                m = ok & ~np.asarray(nulls, dtype=bool)
                if not m.any():
                    continue
                fl = flat[m]
                vv = np.asarray(v)[m]
                # sums accumulate in the column's NATIVE width (int64 for
                # int-backed columns — exact); min/max compare in f64
                np.add.at(acc[c]["sum"], fl, vv.astype(dtypes[c], copy=False))
                vf = vv.astype(np.float64, copy=False)
                np.minimum.at(acc[c]["min"], fl, vf)
                np.maximum.at(acc[c]["max"], fl, vf)
                acc[c]["count"] += np.bincount(fl, minlength=n * n)
        with self._lock:
            for c in missing:
                self.col_grids[c] = {
                    k: g.reshape(n, n) for k, g in acc[c].items()
                }


def _sum_dtype(ft, col: str):
    for a in ft.attributes:
        if a.name == col:
            dt = a.type.numpy_dtype
            if dt is not None and np.dtype(dt).kind in "iub":
                return np.int64
            return np.float64
    return np.float64


def _edges_overlap_rects(edges: np.ndarray, rects: np.ndarray) -> np.ndarray:
    """[K] bool: does any edge segment possibly intersect each rect?
    Conservative (false positives move a cell to the boundary ring —
    cost, never correctness): bbox overlap AND NOT all four rect corners
    strictly on one side of the edge's supporting line."""
    ax, ay, bx, by = edges[:, 0], edges[:, 1], edges[:, 2], edges[:, 3]
    rx0, ry0, rx1, ry1 = rects[:, 0], rects[:, 1], rects[:, 2], rects[:, 3]
    exmin = np.minimum(ax, bx)[None, :]
    exmax = np.maximum(ax, bx)[None, :]
    eymin = np.minimum(ay, by)[None, :]
    eymax = np.maximum(ay, by)[None, :]
    bbox = (
        (exmax >= rx0[:, None]) & (exmin <= rx1[:, None])
        & (eymax >= ry0[:, None]) & (eymin <= ry1[:, None])
    )
    dx = (bx - ax)[None, :]
    dy = (by - ay)[None, :]
    pos = np.zeros_like(bbox)
    neg = np.zeros_like(bbox)
    first = True
    for cx, cy in ((rx0, ry0), (rx0, ry1), (rx1, ry0), (rx1, ry1)):
        cross = dx * (cy[:, None] - ay[None, :]) - dy * (cx[:, None] - ax[None, :])
        if first:
            pos = cross > 0
            neg = cross < 0
            first = False
        else:
            pos &= cross > 0
            neg &= cross < 0
    return (bbox & ~(pos | neg)).any(axis=1)


# -- density-grid query memo --------------------------------------------------


class DensityMemo:
    """One cached density grid (host f64) — the direct query-result leg
    of the GeoBlocks cache: a repeated dashboard tile answers with zero
    dispatch and a bit-identical grid (it IS the stored grid, copied)."""

    __slots__ = ("grid", "last_used", "built_at")

    def __init__(self, grid: np.ndarray):
        self.grid = np.array(grid, dtype=np.float64, copy=True)
        self.built_at = time.time()
        self.last_used = self.built_at

    @property
    def nbytes(self) -> int:
        return int(self.grid.nbytes)

    def evict_device(self) -> None:  # host-only entry
        pass


# -- cache --------------------------------------------------------------------


class AggCache:
    """Per-store TTL'd LRU over pyramid + density-memo entries, bounded
    by total bytes. A generation move re-keys (a stale entry can never
    answer); ``invalidate`` additionally drops a type's entries eagerly
    so a write releases device arrays now, not at TTL."""

    def __init__(self):
        self._entries: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        with _CACHES_LOCK:
            _CACHES.add(self)

    def get(self, key: tuple, ttl_s: float):
        reg = devstats_metrics()
        with self._lock:
            self._sweep(ttl_s)
            e = self._entries.pop(key, None)
            if e is not None:
                self._entries[key] = e  # LRU refresh
                e.last_used = time.time()
                reg.inc("agg.cache.hits")
                reg.inc(f"agg.cache.{key[0]}.hits")
                return e
        reg.inc("agg.cache.misses")
        return None

    def put(self, key: tuple, entry) -> None:
        _bits, _levels, _ttl, cap = agg_knobs()
        reg = devstats_metrics()
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None and old is not entry:
                old.evict_device()
            self._entries[key] = entry
            while len(self._entries) > 1 and self._total_bytes() > cap:
                _k, victim = next(iter(self._entries.items()))
                self._entries.pop(_k).evict_device()
                reg.inc("agg.cache.evicted")

    def invalidate(self, type_name: str) -> int:
        """Drop every entry of ``type_name`` (keys are (kind, type, ...));
        called from the write path so stale levels release immediately."""
        reg = devstats_metrics()
        dropped = 0
        with self._lock:
            for k in [k for k in self._entries if k[1] == type_name]:
                self._entries.pop(k).evict_device()
                dropped += 1
        if dropped:
            reg.inc("agg.cache.invalidated", dropped)
        return dropped

    def _sweep(self, ttl_s: float) -> None:
        """Drop EVERY expired entry (idle pyramids must release HBM at
        TTL — the JoinBuildCache rule). Called under the lock."""
        now = time.time()
        expired = [
            k for k, e in self._entries.items() if now - e.last_used > ttl_s
        ]
        for k in expired:
            self._entries.pop(k).evict_device()
        if expired:
            devstats_metrics().inc("agg.cache.expired", len(expired))

    def _total_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def total_bytes(self) -> int:
        with self._lock:
            return self._total_bytes()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def _cache_totals() -> Tuple[int, int]:
    with _CACHES_LOCK:
        caches = list(_CACHES)
    return sum(len(c) for c in caches), sum(c.total_bytes() for c in caches)


def agg_debug() -> Dict[str, Any]:
    """The ``agg`` block of GET /debug/device: cache occupancy/bytes and
    hit/miss/build/eviction counters, plus the latest pyramid build's
    shape — the operator's "is the aggregate cache earning its HBM"
    answer."""
    reg = devstats_metrics()
    counters, _g, _t, totals = reg.snapshot()
    entries, nbytes = _cache_totals()
    with _LAST_BUILD_LOCK:
        last = dict(_LAST_BUILD)
    build_count, build_sum_s = totals.get("agg.build", (0, 0.0))
    return {
        "cache": {
            "entries": entries,
            "bytes": nbytes,
            "hits": counters.get("agg.cache.hits", 0),
            "misses": counters.get("agg.cache.misses", 0),
            "builds": counters.get("agg.cache.builds", 0),
            "expired": counters.get("agg.cache.expired", 0),
            "evicted": counters.get("agg.cache.evicted", 0),
            "invalidated": counters.get("agg.cache.invalidated", 0),
        },
        "build": {
            "count": build_count,
            "wall_s": round(build_sum_s, 4),
        },
        "pyramid": last,
    }
