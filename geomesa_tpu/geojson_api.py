"""Schema-less GeoJSON API (the geomesa-geojson analog).

Reference: geomesa-geojson (SURVEY.md section 2.5): GeoJsonIndex stores
arbitrary GeoJSON with JSON-path access, GeoJsonQuery translates a mongo-ish
query syntax to CQL. Here GeoJSON features land in a generic point schema
(properties as a JSON string column + extracted geometry/time) and the query
translator covers the documented operator set ($bbox, $eq/$lt/$lte/$gt/$gte,
$and/$or, bare equality).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from geomesa_tpu.schema.featuretype import parse_spec
from geomesa_tpu.store.datastore import TpuDataStore

_SPEC = "props:String,dtg:Date,*geom:Point:srid=4326"


class GeoJsonIndex:
    def __init__(self, store: Optional[TpuDataStore] = None, date_path: str = "dtg"):
        self.store = store or TpuDataStore()
        self.date_path = date_path
        self._names: set = set()

    def create_index(self, name: str) -> None:
        if name not in self._names:
            self.store.create_schema(parse_spec(name, _SPEC))
            self._names.add(name)

    def add(self, name: str, features: Iterable[Dict[str, Any]]) -> List[str]:
        """Add GeoJSON Feature dicts; returns fids."""
        self.create_index(name)
        fids = []
        with self.store.writer(name) as w:
            for f in features:
                geom = f.get("geometry") or {}
                if geom.get("type") != "Point":
                    raise ValueError("GeoJsonIndex v1 indexes Point features")
                x, y = geom["coordinates"][:2]
                props = f.get("properties") or {}
                dtg = props.get(self.date_path)
                if isinstance(dtg, str):
                    dtg = int(
                        np.datetime64(dtg.replace("Z", ""), "ms").astype("int64")
                    )
                from geomesa_tpu.geom.base import Point

                fid = w.write(
                    [json.dumps(props), dtg, Point(float(x), float(y))],
                    fid=f.get("id"),
                )
                fids.append(fid)
        return fids

    # -- queries ------------------------------------------------------------

    def query(self, name: str, q: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
        cql = self.translate(q or {})
        res = self.store.query(name, cql)
        out = []
        xs = res.columns["geom__x"]
        ys = res.columns["geom__y"]
        props = res.columns["props"]
        for i, fid in enumerate(res.fids):
            p = json.loads(props[i]) if props[i] else {}
            feat = {
                "type": "Feature",
                "id": str(fid),
                "geometry": {"type": "Point", "coordinates": [float(xs[i]), float(ys[i])]},
                "properties": p,
            }
            out.append(feat)
        # property-level predicates that CQL can't see run client-side
        residual = self._residual(q or {})
        if residual:
            out = [f for f in out if residual(f["properties"])]
        return out

    # mongo-ish -> CQL translation (GeoJsonQuery analog)

    def translate(self, q: Dict[str, Any]) -> str:
        parts = []
        for key, value in q.items():
            if key == "$bbox":
                xmin, ymin, xmax, ymax = value
                parts.append(f"bbox(geom, {xmin}, {ymin}, {xmax}, {ymax})")
            elif key == "$and":
                parts.append(" AND ".join(f"({self.translate(v)})" for v in value))
            elif key == "$or":
                parts.append(" OR ".join(f"({self.translate(v)})" for v in value))
        return " AND ".join(p for p in parts if p) or "INCLUDE"

    def _residual(self, q: Dict[str, Any]):
        preds = []
        for key, value in q.items():
            if key.startswith("$"):
                continue
            if isinstance(value, dict):
                for op, rhs in value.items():
                    fn = {
                        "$eq": lambda a, b: a == b,
                        "$lt": lambda a, b: a is not None and a < b,
                        "$lte": lambda a, b: a is not None and a <= b,
                        "$gt": lambda a, b: a is not None and a > b,
                        "$gte": lambda a, b: a is not None and a >= b,
                    }.get(op)
                    if fn is None:
                        raise ValueError(f"unsupported operator {op}")
                    preds.append((key, fn, rhs))
            else:
                preds.append((key, lambda a, b: a == b, value))
        if not preds:
            return None

        def check(props: Dict[str, Any]) -> bool:
            for key, fn, rhs in preds:
                cur: Any = props
                for part in key.split("."):
                    cur = cur.get(part) if isinstance(cur, dict) else None
                if not fn(cur, rhs):
                    return False
            return True

        return check
