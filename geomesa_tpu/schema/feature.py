"""A single feature: id + attribute values (ScalaSimpleFeature analog).

Reference: geomesa-features geomesa-feature-common
.../ScalaSimpleFeature.scala:1-157. In the TPU design features mostly live in
columnar blocks (geomesa_tpu.store.blocks); this row-oriented class is the
ingest/egress unit and test currency.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional, Sequence

from geomesa_tpu.geom.base import Geometry
from geomesa_tpu.geom.wkt import parse_wkt
from geomesa_tpu.schema.featuretype import AttributeType, FeatureType


def _to_millis(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, datetime.datetime):
        if v.tzinfo is None:
            v = v.replace(tzinfo=datetime.timezone.utc)
        return int(v.timestamp() * 1000)
    if isinstance(v, str):
        # same parser as filter literals so ingest and queries agree
        from geomesa_tpu.filter.parser import parse_instant_ms

        return parse_instant_ms(v)
    raise TypeError(f"Cannot convert {v!r} to a date")


_CONVERTERS = {
    AttributeType.STRING: lambda v: str(v),
    AttributeType.INT: lambda v: int(v),
    AttributeType.LONG: lambda v: int(v),
    AttributeType.FLOAT: lambda v: float(v),
    AttributeType.DOUBLE: lambda v: float(v),
    AttributeType.BOOLEAN: lambda v: v if isinstance(v, bool) else str(v).lower() == "true",
    AttributeType.DATE: _to_millis,
    AttributeType.UUID: lambda v: str(v),
    AttributeType.BYTES: lambda v: bytes(v),
}


def convert_attribute(type_: AttributeType, value: Any) -> Any:
    """Coerce a raw value to the canonical in-memory representation."""
    if value is None:
        return None
    if type_.is_geometry:
        if isinstance(value, Geometry):
            return value
        if isinstance(value, str):
            return parse_wkt(value)
        raise TypeError(f"Cannot convert {value!r} to a geometry")
    return _CONVERTERS[type_](value)


class Feature:
    __slots__ = ("fid", "values", "user_data")

    def __init__(
        self,
        ft: FeatureType,
        fid: Optional[str],
        values: Sequence[Any],
        user_data: Optional[Dict[str, Any]] = None,
    ):
        if len(values) != len(ft.attributes):
            raise ValueError(
                f"Expected {len(ft.attributes)} values, got {len(values)}"
            )
        self.fid = fid
        self.values: List[Any] = [
            convert_attribute(a.type, v) for a, v in zip(ft.attributes, values)
        ]
        self.user_data = dict(user_data or {})

    def get(self, ft: FeatureType, name: str) -> Any:
        return self.values[ft.index_of(name)]

    def __repr__(self):
        return f"Feature({self.fid!r}, {self.values!r})"
