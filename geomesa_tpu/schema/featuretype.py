"""FeatureType: schema definition + spec-string parser/encoder.

Reference: geomesa-utils .../geotools/SimpleFeatureTypes.scala (spec strings),
SimpleFeatureSpec.scala (attribute options + user-data config keys), and
geomesa-utils .../index/GeoMesaSchemaValidator.scala (dtg binding checks).

Columnar mapping (TPU-first design): every attribute type declares its
storage -- a numpy dtype for fixed-width columns (numbers, dates as epoch
millis, booleans), object/dictionary columns for strings, and coordinate
pairs for point geometries. Non-point geometries store WKT plus a packed
envelope column so device kernels can bbox-reject without parsing.
"""

from __future__ import annotations

import enum
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.curve.binnedtime import TimePeriod


class AttributeType(enum.Enum):
    STRING = "String"
    INT = "Integer"
    LONG = "Long"
    FLOAT = "Float"
    DOUBLE = "Double"
    BOOLEAN = "Boolean"
    DATE = "Date"
    UUID = "UUID"
    BYTES = "Bytes"
    POINT = "Point"
    LINESTRING = "LineString"
    POLYGON = "Polygon"
    MULTIPOINT = "MultiPoint"
    MULTILINESTRING = "MultiLineString"
    MULTIPOLYGON = "MultiPolygon"
    GEOMETRYCOLLECTION = "GeometryCollection"
    GEOMETRY = "Geometry"

    @property
    def is_geometry(self) -> bool:
        return self in _GEOM_TYPES

    @property
    def numpy_dtype(self) -> Optional[np.dtype]:
        """Fixed-width column dtype, or None for variable-width (object) storage."""
        return _NUMPY_DTYPES.get(self)


_GEOM_TYPES = {
    AttributeType.POINT,
    AttributeType.LINESTRING,
    AttributeType.POLYGON,
    AttributeType.MULTIPOINT,
    AttributeType.MULTILINESTRING,
    AttributeType.MULTIPOLYGON,
    AttributeType.GEOMETRYCOLLECTION,
    AttributeType.GEOMETRY,
}

_NUMPY_DTYPES = {
    AttributeType.INT: np.dtype(np.int32),
    AttributeType.LONG: np.dtype(np.int64),
    AttributeType.FLOAT: np.dtype(np.float32),
    AttributeType.DOUBLE: np.dtype(np.float64),
    AttributeType.BOOLEAN: np.dtype(np.bool_),
    AttributeType.DATE: np.dtype(np.int64),  # epoch millis
}

_TYPE_ALIASES = {
    # "json" is storage-wise a String with the json flag set — the
    # reference models it the same way (a String attribute with
    # user-data json=true; KryoJsonSerialization.scala:1-525 stores the
    # parsed document, here the string column is the document of record)
    "json": AttributeType.STRING,
    "string": AttributeType.STRING,
    "int": AttributeType.INT,
    "integer": AttributeType.INT,
    "long": AttributeType.LONG,
    "float": AttributeType.FLOAT,
    "double": AttributeType.DOUBLE,
    "boolean": AttributeType.BOOLEAN,
    "bool": AttributeType.BOOLEAN,
    "date": AttributeType.DATE,
    "timestamp": AttributeType.DATE,
    "uuid": AttributeType.UUID,
    "bytes": AttributeType.BYTES,
    "point": AttributeType.POINT,
    "linestring": AttributeType.LINESTRING,
    "polygon": AttributeType.POLYGON,
    "multipoint": AttributeType.MULTIPOINT,
    "multilinestring": AttributeType.MULTILINESTRING,
    "multipolygon": AttributeType.MULTIPOLYGON,
    "geometrycollection": AttributeType.GEOMETRYCOLLECTION,
    "geometry": AttributeType.GEOMETRY,
}

# reserved words the reference rejects as attribute names (GeoMesaSchemaValidator)
_RESERVED = {"id", "fid"}


class AttributeDescriptor:
    def __init__(
        self,
        name: str,
        type_: AttributeType,
        default_geom: bool = False,
        options: Optional[Dict[str, str]] = None,
    ):
        self.name = name
        self.type = type_
        self.default_geom = default_geom
        self.options = dict(options or {})

    @property
    def indexed(self) -> bool:
        """Attribute-index flag (``index=true`` / ``index=join`` option)."""
        v = self.options.get("index", "false").lower()
        return v in ("true", "full", "join")

    @property
    def json(self) -> bool:
        """JSON-typed String attribute (``:json=true`` or the ``json``
        type alias): path expressions ``$.name.path`` select into the
        stored document (JsonPathPropertyAccessor analog)."""
        return (
            self.type == AttributeType.STRING
            and self.options.get("json", "false").lower() == "true"
        )

    def spec(self) -> str:
        parts = [f"{'*' if self.default_geom else ''}{self.name}:{self.type.value}"]
        for k, v in self.options.items():
            parts.append(f"{k}={v}")
        return ":".join(parts)

    def __repr__(self):
        return f"AttributeDescriptor({self.spec()!r})"

    def __eq__(self, other):
        return isinstance(other, AttributeDescriptor) and (
            self.name,
            self.type,
            self.default_geom,
            self.options,
        ) == (other.name, other.type, other.default_geom, other.options)


class FeatureType:
    """Schema for one feature type (SimpleFeatureType analog).

    ``user_data`` carries schema-level config exactly like the reference's
    SFT user data: ``geomesa.indices`` (enabled index list),
    ``geomesa.z3.interval`` / ``geomesa.xz3.interval`` (time period),
    ``geomesa.z.splits`` (shard count), ``geomesa.table.sharing``, etc.
    """

    def __init__(
        self,
        name: str,
        attributes: List[AttributeDescriptor],
        user_data: Optional[Dict[str, str]] = None,
    ):
        self.name = name
        self.attributes = list(attributes)
        self.user_data: Dict[str, str] = dict(user_data or {})
        self._by_name = {a.name: i for i, a in enumerate(self.attributes)}
        if len(self._by_name) != len(self.attributes):
            raise ValueError("Duplicate attribute names")
        for a in self.attributes:
            if a.name.lower() in _RESERVED:
                raise ValueError(f"Reserved attribute name: {a.name}")

    # -- attribute access ---------------------------------------------------

    def attr(self, name: str) -> AttributeDescriptor:
        return self.attributes[self.index_of(name)]

    def index_of(self, name: str) -> int:
        if name not in self._by_name:
            raise KeyError(f"No attribute {name!r} in type {self.name!r}")
        return self._by_name[name]

    def has(self, name: str) -> bool:
        return name in self._by_name

    @property
    def attribute_names(self) -> List[str]:
        return [a.name for a in self.attributes]

    # -- well-known roles ---------------------------------------------------

    @property
    def default_geometry(self) -> Optional[AttributeDescriptor]:
        for a in self.attributes:
            if a.default_geom:
                return a
        for a in self.attributes:
            if a.type.is_geometry:
                return a
        return None

    @property
    def default_date(self) -> Optional[AttributeDescriptor]:
        """The dtg attribute: explicit via user data, else first Date attribute
        (GeoMesaSchemaValidator's dtg binding)."""
        explicit = self.user_data.get("geomesa.index.dtg")
        if explicit:
            return self.attr(explicit)
        for a in self.attributes:
            if a.type == AttributeType.DATE:
                return a
        return None

    @property
    def z3_interval(self) -> TimePeriod:
        """geomesa.z3.interval user-data key, default week (reference default)."""
        return TimePeriod.parse(self.user_data.get("geomesa.z3.interval", "week"))

    @property
    def xz3_interval(self) -> TimePeriod:
        return TimePeriod.parse(self.user_data.get("geomesa.xz3.interval", "week"))

    @property
    def z_shards(self) -> int:
        """geomesa.z.splits: write-shard count (reference default 4)."""
        return int(self.user_data.get("geomesa.z.splits", "4"))

    @property
    def attribute_shards(self) -> int:
        return int(self.user_data.get("geomesa.attr.splits", "4"))

    @property
    def xz_precision(self) -> int:
        """geomesa.xz.precision: XZ curve resolution g (default 12)."""
        return int(self.user_data.get("geomesa.xz.precision", "12"))

    @property
    def enabled_indices(self) -> Optional[List[str]]:
        """Explicit geomesa.indices user-data override, or None for defaults."""
        raw = self.user_data.get("geomesa.indices.enabled") or self.user_data.get(
            "geomesa.indices"
        )
        if not raw:
            return None
        return [s.strip() for s in raw.split(",") if s.strip()]

    @property
    def is_points(self) -> bool:
        geom = self.default_geometry
        return geom is not None and geom.type == AttributeType.POINT

    # -- spec round trip ----------------------------------------------------

    def spec(self) -> str:
        return encode_spec(self)

    def __repr__(self):
        return f"FeatureType({self.name!r}, {self.spec()!r})"

    def __eq__(self, other):
        return (
            isinstance(other, FeatureType)
            and self.name == other.name
            and self.attributes == other.attributes
            and self.user_data == other.user_data
        )


def parse_spec(name: str, spec: str) -> FeatureType:
    """Parse a spec string into a FeatureType.

    Format (SimpleFeatureTypes.scala / SimpleFeatureSpecParser.scala):
    ``[*]name:Type[:opt=val]*(,...)[;key=value(,key=value)*]``. User-data
    values may be single-quoted.
    """
    spec = spec.strip()
    user_data: Dict[str, str] = {}
    if ";" in spec:
        attr_part, ud_part = spec.split(";", 1)
        for entry in _split_top(ud_part, ","):
            if not entry.strip():
                continue
            if "=" not in entry:
                raise ValueError(f"Bad user-data entry: {entry!r}")
            k, v = entry.split("=", 1)
            user_data[k.strip()] = v.strip().strip("'\"")
    else:
        attr_part = spec

    attrs: List[AttributeDescriptor] = []
    for entry in _split_top(attr_part, ","):
        entry = entry.strip()
        if not entry:
            continue
        default_geom = entry.startswith("*")
        if default_geom:
            entry = entry[1:]
        pieces = entry.split(":")
        if len(pieces) < 2:
            raise ValueError(f"Bad attribute spec: {entry!r}")
        aname = pieces[0].strip()
        tname = pieces[1].strip().lower()
        if tname not in _TYPE_ALIASES:
            raise ValueError(f"Unknown attribute type: {pieces[1]!r}")
        options: Dict[str, str] = {}
        for opt in pieces[2:]:
            if "=" not in opt:
                raise ValueError(f"Bad attribute option: {opt!r}")
            k, v = opt.split("=", 1)
            options[k.strip()] = v.strip().strip("'\"")
        if tname == "json":
            options.setdefault("json", "true")
        if options.get("json", "false").lower() == "true" and (
            _TYPE_ALIASES[tname] != AttributeType.STRING
        ):
            raise ValueError(
                f"json=true requires a String attribute: {entry!r}"
            )
        attrs.append(
            AttributeDescriptor(aname, _TYPE_ALIASES[tname], default_geom, options)
        )
    return FeatureType(name, attrs, user_data)


def encode_spec(ft: FeatureType) -> str:
    attr_part = ",".join(a.spec() for a in ft.attributes)
    if ft.user_data:
        ud = ",".join(f"{k}='{v}'" for k, v in sorted(ft.user_data.items()))
        return f"{attr_part};{ud}"
    return attr_part


def _split_top(s: str, sep: str) -> List[str]:
    """Split on ``sep`` outside of quotes."""
    out, buf, quote = [], [], None
    for ch in s:
        if quote:
            buf.append(ch)
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            buf.append(ch)
        elif ch == sep:
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf))
    return out
