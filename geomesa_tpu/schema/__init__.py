"""Feature type schema: the SimpleFeatureType analog.

Rebuild of the reference's spec-string driven schema layer
(geomesa-utils .../geotools/SimpleFeatureTypes.scala and
SimpleFeatureSpecParser.scala): a feature type is declared as
``"name:String,age:Int,dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval=week"``
-- comma-separated ``name:Type[:opt=val...]`` attribute specs, ``*`` marking
the default geometry, and semicolon-separated user-data entries carrying
schema-level configuration (enabled indices, z3 interval, shard counts...).
"""

from geomesa_tpu.schema.featuretype import (
    AttributeDescriptor,
    AttributeType,
    FeatureType,
    parse_spec,
    encode_spec,
)
from geomesa_tpu.schema.feature import Feature

__all__ = [
    "AttributeDescriptor",
    "AttributeType",
    "FeatureType",
    "Feature",
    "parse_spec",
    "encode_spec",
]
