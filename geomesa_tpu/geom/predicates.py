"""Vectorized spatial predicates over coordinate arrays.

The host-side (numpy) versions of the post-filter kernels. These evaluate a
*query geometry* against columnar batches of feature points -- the analog of
the reference's CQL geometry predicates evaluated per-feature in server-side
iterators (e.g. KryoLazyFilterTransformIterator). The same math is mirrored
on device in ``geomesa_tpu.ops.geometry``.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geom.base import (
    Envelope,
    Geometry,
    GeometryCollection,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)


def points_in_envelope(x: np.ndarray, y: np.ndarray, env: Envelope) -> np.ndarray:
    """Inclusive bbox containment for point arrays."""
    return (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)


def _points_in_ring(x: np.ndarray, y: np.ndarray, ring: np.ndarray) -> np.ndarray:
    """Even-odd ray cast: True where (x, y) is strictly inside or on an edge
    crossing. Boundary points are handled separately by the on-segment test."""
    inside = np.zeros(x.shape, dtype=bool)
    x0, y0 = ring[:-1, 0], ring[:-1, 1]
    x1, y1 = ring[1:, 0], ring[1:, 1]
    for i in range(len(x0)):
        ax, ay, bx, by = x0[i], y0[i], x1[i], y1[i]
        crosses = ((ay > y) != (by > y)) & (
            x < (bx - ax) * (y - ay) / np.where(by != ay, by - ay, 1.0) + ax
        )
        inside ^= crosses
    return inside


def _points_on_segments(x: np.ndarray, y: np.ndarray, ring: np.ndarray, eps=1e-12):
    """True where a point lies on any segment of the ring (inclusive ends)."""
    on = np.zeros(x.shape, dtype=bool)
    x0, y0 = ring[:-1, 0], ring[:-1, 1]
    x1, y1 = ring[1:, 0], ring[1:, 1]
    for i in range(len(x0)):
        ax, ay, bx, by = x0[i], y0[i], x1[i], y1[i]
        cross = (bx - ax) * (y - ay) - (by - ay) * (x - ax)
        within = (
            (np.minimum(ax, bx) - eps <= x)
            & (x <= np.maximum(ax, bx) + eps)
            & (np.minimum(ay, by) - eps <= y)
            & (y <= np.maximum(ay, by) + eps)
        )
        on |= (np.abs(cross) <= eps * max(1.0, abs(bx - ax) + abs(by - ay))) & within
    return on


def points_in_polygon(
    x: np.ndarray, y: np.ndarray, poly: Polygon, boundary: bool = True
) -> np.ndarray:
    """Point-in-polygon. ``boundary=True`` includes shell *and* hole rings
    (JTS intersects semantics); ``boundary=False`` is the strict interior
    (JTS within semantics for points)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    env = poly.envelope
    candidates = points_in_envelope(x, y, env)
    result = np.zeros(x.shape, dtype=bool)
    if not candidates.any():
        return result
    xi, yi = x[candidates], y[candidates]
    inside = _points_in_ring(xi, yi, poly.shell)
    for hole in poly.holes:
        inside &= ~_points_in_ring(xi, yi, hole)
    on_boundary = _points_on_segments(xi, yi, poly.shell)
    for hole in poly.holes:
        on_boundary |= _points_on_segments(xi, yi, hole)
    if boundary:
        inside |= on_boundary
    else:
        inside &= ~on_boundary
    result[candidates] = inside
    return result


def points_in_geometry(x: np.ndarray, y: np.ndarray, geom: Geometry) -> np.ndarray:
    """Does each point intersect ``geom``? Dispatch over geometry type."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if isinstance(geom, Polygon):
        if geom.is_rectangle():
            return points_in_envelope(x, y, geom.envelope)
        return points_in_polygon(x, y, geom)
    if isinstance(geom, Point):
        return (x == geom.x) & (y == geom.y)
    if isinstance(geom, LineString):
        return _points_on_segments(x, y, geom.coords)
    if isinstance(geom, (MultiPolygon, MultiPoint, MultiLineString, GeometryCollection)):
        out = np.zeros(x.shape, dtype=bool)
        for g in geom.geoms:
            out |= points_in_geometry(x, y, g)
        return out
    raise ValueError(f"Unsupported geometry for point test: {type(geom)}")


def segments_intersect_envelope(coords: np.ndarray, env: Envelope) -> bool:
    """Does a polyline intersect an envelope? (Used for non-point features.)

    Cohen-Sutherland style: any endpoint inside, or any segment straddling.
    """
    x, y = coords[:, 0], coords[:, 1]
    if points_in_envelope(x, y, env).any():
        return True
    # check each segment against the 4 envelope edges
    corners = env.to_polygon().shell
    for i in range(len(coords) - 1):
        p, q = coords[i], coords[i + 1]
        for j in range(4):
            a, b = corners[j], corners[j + 1]
            if _segs_cross(p, q, a, b):
                return True
    return False


def _segs_cross(p, q, a, b) -> bool:
    d1 = _orient(a, b, p)
    d2 = _orient(a, b, q)
    d3 = _orient(p, q, a)
    d4 = _orient(p, q, b)
    if ((d1 > 0) != (d2 > 0)) and ((d3 > 0) != (d4 > 0)):
        return True
    for pt, (u, v) in [(p, (a, b)), (q, (a, b)), (a, (p, q)), (b, (p, q))]:
        if _orient(u, v, pt) == 0 and _on_segment(u, v, pt):
            return True
    return False


def _orient(a, b, c) -> float:
    return (b[0] - a[0]) * (c[1] - a[1]) - (b[1] - a[1]) * (c[0] - a[0])


def _on_segment(a, b, c) -> bool:
    return (
        min(a[0], b[0]) <= c[0] <= max(a[0], b[0])
        and min(a[1], b[1]) <= c[1] <= max(a[1], b[1])
    )


# ---------------------------------------------------------------------------
# exact geometry-geometry intersects / distance (the JTS relate subset)
# ---------------------------------------------------------------------------


def _rings(geom: Geometry):
    """All coordinate rings/paths of a geometry."""
    if isinstance(geom, Point):
        yield geom.coords
    elif isinstance(geom, LineString):
        yield geom.coords
    elif isinstance(geom, Polygon):
        yield geom.shell
        yield from geom.holes
    else:
        for g in geom.geoms:
            yield from _rings(g)


def _paths_cross(a: np.ndarray, b: np.ndarray) -> bool:
    for i in range(len(a) - 1):
        for j in range(len(b) - 1):
            if _segs_cross(a[i], a[i + 1], b[j], b[j + 1]):
                return True
    return False


def _paths_properly_cross(a: np.ndarray, b: np.ndarray) -> bool:
    """Proper (transversal) crossings only -- touching endpoints or running
    along a boundary does not count. Used by within-tests where boundary
    contact is allowed."""
    for i in range(len(a) - 1):
        p, q = a[i], a[i + 1]
        for j in range(len(b) - 1):
            u, v = b[j], b[j + 1]
            d1 = _orient(u, v, p)
            d2 = _orient(u, v, q)
            d3 = _orient(p, q, u)
            d4 = _orient(p, q, v)
            if ((d1 > 0) != (d2 > 0)) and ((d3 > 0) != (d4 > 0)) and 0 not in (d1, d2, d3, d4):
                return True
    return False


def geometries_intersect(g1: Geometry, g2: Geometry) -> bool:
    """Exact intersects for the supported types (boundary inclusive).

    Covers the combinations the post-filter needs: point/line/polygon and
    their multis. Envelope-rejects first, then tests containment of
    representative vertices plus pairwise edge crossings.
    """
    if not g1.envelope.intersects(g2.envelope):
        return False
    # axis-aligned rectangles ARE their envelopes: overlap decides exactly
    # (the dominant case in bbox post-filter rings)
    if g1.is_rectangle() and g2.is_rectangle():
        return True
    if isinstance(g1, (MultiPoint, MultiLineString, MultiPolygon, GeometryCollection)):
        return any(geometries_intersect(g, g2) for g in g1.geoms)
    if isinstance(g2, (MultiPoint, MultiLineString, MultiPolygon, GeometryCollection)):
        return any(geometries_intersect(g1, g) for g in g2.geoms)
    if isinstance(g1, Point):
        return bool(points_in_geometry(np.array([g1.x]), np.array([g1.y]), g2)[0])
    if isinstance(g2, Point):
        return bool(points_in_geometry(np.array([g2.x]), np.array([g2.y]), g1)[0])
    # line/polygon vs line/polygon: vertex containment either way, or edge cross
    p1 = next(iter(_rings(g1)))
    p2 = next(iter(_rings(g2)))
    if bool(points_in_geometry(p1[:1, 0], p1[:1, 1], g2)[0]):
        return True
    if bool(points_in_geometry(p2[:1, 0], p2[:1, 1], g1)[0]):
        return True
    for a in _rings(g1):
        for b in _rings(g2):
            if _paths_cross(a, b):
                return True
    return False


def points_within_geometry(x: np.ndarray, y: np.ndarray, geom: Geometry) -> np.ndarray:
    """JTS within for point arrays: interior containment, so points on a
    polygon boundary are excluded (unlike :func:`points_in_geometry`)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if isinstance(geom, Polygon):
        return points_in_polygon(x, y, geom, boundary=False)
    if isinstance(geom, (MultiPolygon, GeometryCollection, MultiPoint, MultiLineString)):
        out = np.zeros(x.shape, dtype=bool)
        for g in geom.geoms:
            out |= points_within_geometry(x, y, g)
        return out
    return points_in_geometry(x, y, geom)


def points_distance_to_geometry(
    x: np.ndarray, y: np.ndarray, geom: Geometry
) -> np.ndarray:
    """Exact degree-space distance from each point to ``geom`` (0 inside)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if isinstance(geom, Point):
        return np.sqrt((x - geom.x) ** 2 + (y - geom.y) ** 2)
    if isinstance(geom, (MultiPolygon, MultiPoint, MultiLineString, GeometryCollection)):
        out = np.full(x.shape, np.inf)
        for g in geom.geoms:
            out = np.minimum(out, points_distance_to_geometry(x, y, g))
        return out
    d2 = np.full(x.shape, np.inf)
    for ring in _rings(geom):
        if len(ring) == 1:
            d2 = np.minimum(d2, (x - ring[0, 0]) ** 2 + (y - ring[0, 1]) ** 2)
        for i in range(len(ring) - 1):
            a, b = ring[i], ring[i + 1]
            abx, aby = b[0] - a[0], b[1] - a[1]
            denom = abx * abx + aby * aby
            t = np.clip(
                ((x - a[0]) * abx + (y - a[1]) * aby) / (denom if denom else 1.0),
                0.0,
                1.0,
            )
            dx = x - (a[0] + t * abx)
            dy = y - (a[1] + t * aby)
            d2 = np.minimum(d2, dx * dx + dy * dy)
    dist = np.sqrt(d2)
    if isinstance(geom, Polygon):
        inside = points_in_polygon(x, y, geom)
        dist = np.where(inside, 0.0, dist)
    return dist


def geometry_within(g1: Geometry, g2: Geometry) -> bool:
    """g1 within g2 (g1 entirely contained; point-on-boundary excluded for
    point g1, matching JTS where within requires interior intersection)."""
    if not g2.envelope.contains_env(g1.envelope):
        return False
    if isinstance(g1, Point):
        if isinstance(g2, Polygon):
            return bool(
                points_in_polygon(np.array([g1.x]), np.array([g1.y]), g2, boundary=False)[0]
            )
        if isinstance(g2, (MultiPolygon, GeometryCollection)):
            return any(geometry_within(g1, g) for g in g2.geoms)
        return bool(points_in_geometry(np.array([g1.x]), np.array([g1.y]), g2)[0])
    if isinstance(g1, (MultiPoint, MultiLineString, MultiPolygon, GeometryCollection)):
        return all(geometry_within(g, g2) for g in g1.geoms)
    # every vertex and edge midpoint inside (hole-aware), and no edge
    # properly crossing any ring of g2 (boundary contact allowed)
    for path in _rings(g1):
        mask = points_in_geometry(path[:, 0], path[:, 1], g2)
        if not mask.all():
            return False
        if len(path) > 1:
            mx = (path[:-1, 0] + path[1:, 0]) / 2.0
            my = (path[:-1, 1] + path[1:, 1]) / 2.0
            if not points_in_geometry(mx, my, g2).all():
                return False
    for a in _rings(g1):
        for b in _rings(g2):
            if _paths_properly_cross(a, b):
                return False
    return True


def _seg_seg_dist2(p, q, a, b) -> float:
    """Squared distance between segments pq and ab."""
    if _segs_cross(p, q, a, b):
        return 0.0
    return min(
        _pt_seg_dist2(p, a, b),
        _pt_seg_dist2(q, a, b),
        _pt_seg_dist2(a, p, q),
        _pt_seg_dist2(b, p, q),
    )


def _pt_seg_dist2(c, a, b) -> float:
    abx, aby = b[0] - a[0], b[1] - a[1]
    denom = abx * abx + aby * aby
    if denom == 0:
        dx, dy = c[0] - a[0], c[1] - a[1]
        return dx * dx + dy * dy
    t = max(0.0, min(1.0, ((c[0] - a[0]) * abx + (c[1] - a[1]) * aby) / denom))
    dx = c[0] - (a[0] + t * abx)
    dy = c[1] - (a[1] + t * aby)
    return dx * dx + dy * dy


def geometry_distance(g1: Geometry, g2: Geometry) -> float:
    """Min euclidean (degree-space) distance; 0 when intersecting."""
    if geometries_intersect(g1, g2):
        return 0.0
    best = np.inf
    for a in _rings(g1):
        for b in _rings(g2):
            if len(a) == 1 and len(b) == 1:
                d2 = (a[0, 0] - b[0, 0]) ** 2 + (a[0, 1] - b[0, 1]) ** 2
            elif len(a) == 1:
                d2 = min(
                    _pt_seg_dist2(a[0], b[j], b[j + 1]) for j in range(len(b) - 1)
                )
            elif len(b) == 1:
                d2 = min(
                    _pt_seg_dist2(b[0], a[i], a[i + 1]) for i in range(len(a) - 1)
                )
            else:
                d2 = min(
                    _seg_seg_dist2(a[i], a[i + 1], b[j], b[j + 1])
                    for i in range(len(a) - 1)
                    for j in range(len(b) - 1)
                )
            best = min(best, d2)
    return float(np.sqrt(best))
