"""WKT parsing and writing for the seven simple-feature geometry types.

Replaces JTS's WKTReader/WKTWriter for the framework's needs (converter
ingest, CQL literals, CLI export).
"""

from __future__ import annotations

import re
from typing import List, Tuple

import numpy as np

from geomesa_tpu.geom.base import (
    Geometry,
    GeometryCollection,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

class _Cursor:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def skip_ws(self):
        while self.pos < len(self.text) and self.text[self.pos].isspace():
            self.pos += 1

    def peek(self) -> str:
        self.skip_ws()
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def expect(self, ch: str):
        self.skip_ws()
        if self.pos >= len(self.text) or self.text[self.pos] != ch:
            raise ValueError(
                f"WKT parse error at {self.pos}: expected {ch!r} in {self.text!r}"
            )
        self.pos += 1

    def word(self) -> str:
        self.skip_ws()
        m = re.match(r"[A-Za-z]+", self.text[self.pos :])
        if not m:
            raise ValueError(f"WKT parse error at {self.pos} in {self.text!r}")
        self.pos += m.end()
        return m.group(0).upper()

    def number(self) -> float:
        self.skip_ws()
        m = re.match(r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?", self.text[self.pos :])
        if not m:
            raise ValueError(f"WKT number expected at {self.pos} in {self.text!r}")
        self.pos += m.end()
        return float(m.group(0))


def _parse_coords(c: _Cursor) -> np.ndarray:
    c.expect("(")
    pts: List[Tuple[float, float]] = []
    while True:
        x = c.number()
        y = c.number()
        # ignore any Z/M ordinates
        while c.peek() not in (",", ")"):
            c.number()
        pts.append((x, y))
        if c.peek() == ",":
            c.expect(",")
        else:
            break
    c.expect(")")
    return np.array(pts, dtype=np.float64)


def _parse_rings(c: _Cursor) -> List[np.ndarray]:
    c.expect("(")
    rings = [_parse_coords(c)]
    while c.peek() == ",":
        c.expect(",")
        rings.append(_parse_coords(c))
    c.expect(")")
    return rings


def _parse_geom(c: _Cursor) -> Geometry:
    kind = c.word()
    if kind == "POINT":
        pts = _parse_coords(c)
        return Point(pts[0, 0], pts[0, 1])
    if kind == "LINESTRING":
        return LineString(_parse_coords(c))
    if kind == "POLYGON":
        rings = _parse_rings(c)
        return Polygon(rings[0], rings[1:])
    if kind == "MULTIPOINT":
        c.expect("(")
        pts = []
        while True:
            if c.peek() == "(":
                sub = _parse_coords(c)
                pts.append(Point(sub[0, 0], sub[0, 1]))
            else:
                pts.append(Point(c.number(), c.number()))
            if c.peek() == ",":
                c.expect(",")
            else:
                break
        c.expect(")")
        return MultiPoint(pts)
    if kind == "MULTILINESTRING":
        c.expect("(")
        lines = [LineString(_parse_coords(c))]
        while c.peek() == ",":
            c.expect(",")
            lines.append(LineString(_parse_coords(c)))
        c.expect(")")
        return MultiLineString(lines)
    if kind == "MULTIPOLYGON":
        c.expect("(")
        polys = []
        rings = _parse_rings(c)
        polys.append(Polygon(rings[0], rings[1:]))
        while c.peek() == ",":
            c.expect(",")
            rings = _parse_rings(c)
            polys.append(Polygon(rings[0], rings[1:]))
        c.expect(")")
        return MultiPolygon(polys)
    if kind == "GEOMETRYCOLLECTION":
        c.expect("(")
        geoms = [_parse_geom(c)]
        while c.peek() == ",":
            c.expect(",")
            geoms.append(_parse_geom(c))
        c.expect(")")
        return GeometryCollection(geoms)
    raise ValueError(f"Unsupported WKT type: {kind}")


def parse_wkt(text: str) -> Geometry:
    c = _Cursor(text)
    g = _parse_geom(c)
    c.skip_ws()
    if c.pos != len(c.text):
        raise ValueError(f"Trailing WKT content: {text[c.pos:]!r}")
    return g


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _coords_str(coords: np.ndarray) -> str:
    return "(" + ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in coords) + ")"


def to_wkt(g: Geometry) -> str:
    if isinstance(g, Point):
        return f"POINT ({_fmt(g.x)} {_fmt(g.y)})"
    if isinstance(g, LineString):
        return "LINESTRING " + _coords_str(g.coords)
    if isinstance(g, Polygon):
        rings = [g.shell] + g.holes
        return "POLYGON (" + ", ".join(_coords_str(r) for r in rings) + ")"
    if isinstance(g, MultiPoint):
        return "MULTIPOINT (" + ", ".join(
            f"({_fmt(p.x)} {_fmt(p.y)})" for p in g.geoms
        ) + ")"
    if isinstance(g, MultiLineString):
        return "MULTILINESTRING (" + ", ".join(
            _coords_str(l.coords) for l in g.geoms
        ) + ")"
    if isinstance(g, MultiPolygon):
        parts = []
        for p in g.geoms:
            rings = [p.shell] + p.holes
            parts.append("(" + ", ".join(_coords_str(r) for r in rings) + ")")
        return "MULTIPOLYGON (" + ", ".join(parts) + ")"
    if isinstance(g, GeometryCollection):
        return "GEOMETRYCOLLECTION (" + ", ".join(to_wkt(m) for m in g.geoms) + ")"
    raise ValueError(f"Cannot serialize {type(g)}")
