"""Lightweight geometry model with numpy coordinate arrays.

Replaces the reference's dependency on JTS (com.vividsolutions.jts) for the
subset of geometry the framework needs: WKT round-trips, envelopes, and the
spatial predicates used by query planning and post-filtering. Coordinates are
(N, 2) float64 arrays -- friendly to columnar storage and to batched device
predicates in ``geomesa_tpu.ops``.
"""

from geomesa_tpu.geom.base import (
    Envelope,
    Geometry,
    GeometryCollection,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    WHOLE_WORLD,
)
from geomesa_tpu.geom.wkt import parse_wkt, to_wkt
from geomesa_tpu.geom.predicates import (
    points_in_envelope,
    points_in_geometry,
    points_in_polygon,
    segments_intersect_envelope,
)

__all__ = [
    "Envelope",
    "Geometry",
    "GeometryCollection",
    "LineString",
    "MultiLineString",
    "MultiPoint",
    "MultiPolygon",
    "Point",
    "Polygon",
    "WHOLE_WORLD",
    "parse_wkt",
    "to_wkt",
    "points_in_envelope",
    "points_in_geometry",
    "points_in_polygon",
    "segments_intersect_envelope",
]
