"""Geometry types: envelope + the seven OGC simple-feature geometries.

The subset of JTS behavior the reference actually leans on (envelope
computation for index keys via ``geometry.getEnvelopeInternal``, intersection
testing for planning/post-filter, WKT round-trips for converters/CLI).
Coordinates are numpy (N, 2) float64 arrays.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np


class Envelope:
    """Axis-aligned bounding box (analog of JTS Envelope)."""

    __slots__ = ("xmin", "ymin", "xmax", "ymax")

    def __init__(self, xmin: float, ymin: float, xmax: float, ymax: float):
        self.xmin = float(xmin)
        self.ymin = float(ymin)
        self.xmax = float(xmax)
        self.ymax = float(ymax)

    @classmethod
    def of_coords(cls, coords: np.ndarray) -> "Envelope":
        return cls(
            coords[:, 0].min(), coords[:, 1].min(), coords[:, 0].max(), coords[:, 1].max()
        )

    def intersects(self, other: "Envelope") -> bool:
        return (
            self.xmin <= other.xmax
            and other.xmin <= self.xmax
            and self.ymin <= other.ymax
            and other.ymin <= self.ymax
        )

    def contains_env(self, other: "Envelope") -> bool:
        return (
            self.xmin <= other.xmin
            and other.xmax <= self.xmax
            and self.ymin <= other.ymin
            and other.ymax <= self.ymax
        )

    def intersection(self, other: "Envelope") -> Optional["Envelope"]:
        if not self.intersects(other):
            return None
        return Envelope(
            max(self.xmin, other.xmin),
            max(self.ymin, other.ymin),
            min(self.xmax, other.xmax),
            min(self.ymax, other.ymax),
        )

    def expand_to_include(self, other: "Envelope") -> "Envelope":
        return Envelope(
            min(self.xmin, other.xmin),
            min(self.ymin, other.ymin),
            max(self.xmax, other.xmax),
            max(self.ymax, other.ymax),
        )

    @property
    def width(self) -> float:
        return self.xmax - self.xmin

    @property
    def height(self) -> float:
        return self.ymax - self.ymin

    @property
    def area(self) -> float:
        return max(0.0, self.width) * max(0.0, self.height)

    def to_polygon(self) -> "Polygon":
        return Polygon(
            np.array(
                [
                    [self.xmin, self.ymin],
                    [self.xmax, self.ymin],
                    [self.xmax, self.ymax],
                    [self.xmin, self.ymax],
                    [self.xmin, self.ymin],
                ]
            )
        )

    def as_tuple(self) -> Tuple[float, float, float, float]:
        return (self.xmin, self.ymin, self.xmax, self.ymax)

    def __eq__(self, other):
        return isinstance(other, Envelope) and self.as_tuple() == other.as_tuple()

    def __hash__(self):
        return hash(self.as_tuple())

    def __repr__(self):
        return f"Envelope({self.xmin}, {self.ymin}, {self.xmax}, {self.ymax})"


class Geometry:
    """Base geometry. Subclasses store coordinates as (N, 2) float64."""

    geom_type = "Geometry"

    @property
    def envelope(self) -> Envelope:
        # memoized: geometries are immutable and the envelope is read on
        # every predicate evaluation (hot in XZ post-filter rings)
        env = getattr(self, "_env_cache", None)
        if env is None:
            env = self._compute_envelope()
            self._env_cache = env
        return env

    def _compute_envelope(self) -> Envelope:
        raise NotImplementedError

    def is_rectangle(self) -> bool:
        """True when the geometry is exactly its envelope (the reference's
        loose-bbox fast path checks geometry==envelope)."""
        return False

    def __repr__(self):
        from geomesa_tpu.geom.wkt import to_wkt

        return to_wkt(self)

    def __eq__(self, other):
        from geomesa_tpu.geom.wkt import to_wkt

        return isinstance(other, Geometry) and to_wkt(self) == to_wkt(other)

    def __hash__(self):
        from geomesa_tpu.geom.wkt import to_wkt

        return hash(to_wkt(self))


class Point(Geometry):
    geom_type = "Point"

    def __init__(self, x: float, y: float):
        self.x = float(x)
        self.y = float(y)

    @property
    def coords(self) -> np.ndarray:
        return np.array([[self.x, self.y]], dtype=np.float64)

    def _compute_envelope(self) -> Envelope:
        return Envelope(self.x, self.y, self.x, self.y)


class LineString(Geometry):
    geom_type = "LineString"

    def __init__(self, coords):
        self.coords = np.asarray(coords, dtype=np.float64).reshape(-1, 2)

    def _compute_envelope(self) -> Envelope:
        return Envelope.of_coords(self.coords)


class Polygon(Geometry):
    """Exterior shell + optional interior holes; rings are closed (N, 2)."""

    geom_type = "Polygon"

    def __init__(self, shell, holes: Optional[Sequence] = None):
        self.shell = np.asarray(shell, dtype=np.float64).reshape(-1, 2)
        self.holes: List[np.ndarray] = [
            np.asarray(h, dtype=np.float64).reshape(-1, 2) for h in (holes or [])
        ]

    def _compute_envelope(self) -> Envelope:
        return Envelope.of_coords(self.shell)

    def is_rectangle(self) -> bool:
        got = getattr(self, "_rect_cache", None)
        if got is None:
            got = self._compute_is_rectangle()
            self._rect_cache = got
        return got

    def _compute_is_rectangle(self) -> bool:
        if self.holes or len(self.shell) != 5:
            return False
        s = [(float(x), float(y)) for x, y in self.shell]
        if s[4] != s[0]:
            return False  # unclosed ring
        env = self.envelope
        corners = {
            (env.xmin, env.ymin),
            (env.xmax, env.ymin),
            (env.xmax, env.ymax),
            (env.xmin, env.ymax),
        }
        if len(corners) != 4 or set(s[:4]) != corners:
            return False
        # perimeter order: consecutive corners must share exactly one
        # coordinate (axis-aligned edges) — rejects self-intersecting
        # "bowtie" orderings whose vertex SET still equals the corners
        # (JTS isRectangle validates ordering the same way)
        return all(
            (x0 == x1) != (y0 == y1)
            for (x0, y0), (x1, y1) in zip(s[:4], s[1:5])
        )


class _Multi(Geometry):
    member_type: type = Geometry

    def __init__(self, geoms: Iterable[Geometry]):
        self.geoms: List[Geometry] = list(geoms)

    def _compute_envelope(self) -> Envelope:
        env = self.geoms[0].envelope
        for g in self.geoms[1:]:
            env = env.expand_to_include(g.envelope)
        return env


class MultiPoint(_Multi):
    geom_type = "MultiPoint"
    member_type = Point


class MultiLineString(_Multi):
    geom_type = "MultiLineString"
    member_type = LineString


class MultiPolygon(_Multi):
    geom_type = "MultiPolygon"
    member_type = Polygon


class GeometryCollection(_Multi):
    geom_type = "GeometryCollection"


# The reference's WholeWorldPolygon (geomesa-utils .../geotools/package.scala)
WHOLE_WORLD = Envelope(-180.0, -90.0, 180.0, 90.0)
