"""Spatially indexed blob storage (the geomesa-blobstore analog).

Reference: geomesa-blobstore (SURVEY.md section 2.5): AccumuloBlobStore keeps
a blob table plus a feature index over geo metadata extracted by FileHandler
SPIs (EXIF/GDAL). Here blobs land on the local filesystem (or in memory) and
their extracted (x, y, dtg, metadata) rows go through the normal datastore,
so bbox/time queries locate files.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.geom.base import Point
from geomesa_tpu.schema.featuretype import parse_spec
from geomesa_tpu.store.datastore import TpuDataStore

_SPEC = "filename:String,meta:String,dtg:Date,*geom:Point:srid=4326"


class FileHandler:
    """SPI: extract (x, y, t_ms, metadata) from file bytes (the EXIF/GDAL
    handler role). ``can_handle`` by filename; return None when unknown."""

    def can_handle(self, filename: str) -> bool:
        raise NotImplementedError

    def extract(self, filename: str, data: bytes):
        raise NotImplementedError


class GeoJsonFileHandler(FileHandler):
    """Handles .geojson files: indexes the first point's location."""

    def can_handle(self, filename: str) -> bool:
        return filename.endswith(".geojson") or filename.endswith(".json")

    def extract(self, filename: str, data: bytes):
        doc = json.loads(data)
        feats = doc.get("features") or ([doc] if doc.get("geometry") else [])
        for f in feats:
            g = f.get("geometry") or {}
            if g.get("type") == "Point":
                x, y = g["coordinates"][:2]
                props = f.get("properties") or {}
                t = props.get("dtg")
                if isinstance(t, str):
                    t = int(np.datetime64(t.replace("Z", ""), "ms").astype("int64"))
                return float(x), float(y), t, props
        return None


class BlobStore:
    def __init__(
        self,
        root: Optional[str] = None,
        store: Optional[TpuDataStore] = None,
        handlers: Optional[List[FileHandler]] = None,
    ):
        self.root = root
        if root:
            os.makedirs(root, exist_ok=True)
        self._mem: Dict[str, bytes] = {}
        self.store = store or TpuDataStore()
        self.store.create_schema(parse_spec("blobs", _SPEC))
        self.handlers = handlers if handlers is not None else [GeoJsonFileHandler()]

    def _blob_id(self, data: bytes) -> str:
        return hashlib.blake2b(data, digest_size=16).hexdigest()

    def put(
        self,
        filename: str,
        data: bytes,
        x: Optional[float] = None,
        y: Optional[float] = None,
        t_ms: Optional[int] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Store a blob; coordinates come from args or a matching handler."""
        if x is None or y is None:
            for h in self.handlers:
                if h.can_handle(filename):
                    got = h.extract(filename, data)
                    if got is not None:
                        x, y, ht, hmeta = got
                        t_ms = t_ms if t_ms is not None else ht
                        metadata = metadata if metadata is not None else hmeta
                        break
        if x is None or y is None:
            raise ValueError(f"no location for blob {filename!r} (no handler matched)")
        blob_id = self._blob_id(data)
        if self.root:
            with open(os.path.join(self.root, blob_id), "wb") as fh:
                fh.write(data)
        else:
            self._mem[blob_id] = data
        with self.store.writer("blobs") as w:
            w.write(
                [filename, json.dumps(metadata or {}), t_ms, Point(x, y)],
                fid=blob_id,
            )
        return blob_id

    def get(self, blob_id: str) -> Optional[bytes]:
        if self.root:
            path = os.path.join(self.root, blob_id)
            if os.path.exists(path):
                with open(path, "rb") as fh:
                    return fh.read()
            return None
        return self._mem.get(blob_id)

    def delete(self, blob_id: str) -> None:
        if self.root:
            path = os.path.join(self.root, blob_id)
            if os.path.exists(path):
                os.remove(path)
        else:
            self._mem.pop(blob_id, None)
        self.store.delete_features("blobs", [blob_id])

    def query(self, cql: str = "INCLUDE") -> List[Dict[str, Any]]:
        """[{id, filename, x, y, dtg, metadata}] matching the CQL."""
        res = self.store.query("blobs", cql)
        out = []
        for i, fid in enumerate(res.fids):
            out.append(
                {
                    "id": str(fid),
                    "filename": res.columns["filename"][i],
                    "x": float(res.columns["geom__x"][i]),
                    "y": float(res.columns["geom__y"][i]),
                    "dtg": int(res.columns["dtg"][i]),
                    "metadata": json.loads(res.columns["meta"][i] or "{}"),
                }
            )
        return out
