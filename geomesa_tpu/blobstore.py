"""Spatially indexed blob storage (the geomesa-blobstore analog).

Reference: geomesa-blobstore (SURVEY.md section 2.5): AccumuloBlobStore keeps
a blob table plus a feature index over geo metadata extracted by FileHandler
SPIs (EXIF/GDAL). Here blobs land on the local filesystem (or in memory) and
their extracted (x, y, dtg, metadata) rows go through the normal datastore,
so bbox/time queries locate files.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.geom.base import Point
from geomesa_tpu.schema.featuretype import parse_spec
from geomesa_tpu.store.datastore import TpuDataStore
from geomesa_tpu.store.integrity import cleanup_tmp, durable_write
from geomesa_tpu.utils import deadline, faults, trace
from geomesa_tpu.utils.retry import RetryPolicy

_SPEC = "filename:String,meta:String,dtg:Date,*geom:Point:srid=4326"

# blob bytes ride the same fault points and retry treatment as store
# blocks: transient I/O failures retry, then surface
_BLOB_RETRY = RetryPolicy(name="blobstore", max_attempts=4, base_s=0.005,
                          cap_s=0.1)


class FileHandler:
    """SPI: extract (x, y, t_ms, metadata) from file bytes (the EXIF/GDAL
    handler role). ``can_handle`` by filename; return None when unknown."""

    def can_handle(self, filename: str) -> bool:
        raise NotImplementedError

    def extract(self, filename: str, data: bytes):
        raise NotImplementedError


class GeoJsonFileHandler(FileHandler):
    """Handles .geojson files: indexes the first point's location."""

    def can_handle(self, filename: str) -> bool:
        return filename.endswith(".geojson") or filename.endswith(".json")

    def extract(self, filename: str, data: bytes):
        doc = json.loads(data)
        feats = doc.get("features") or ([doc] if doc.get("geometry") else [])
        for f in feats:
            g = f.get("geometry") or {}
            if g.get("type") == "Point":
                x, y = g["coordinates"][:2]
                props = f.get("properties") or {}
                t = props.get("dtg")
                if isinstance(t, str):
                    t = int(np.datetime64(t.replace("Z", ""), "ms").astype("int64"))
                return float(x), float(y), t, props
        return None


class ExifFileHandler(FileHandler):
    """Handles geotagged JPEGs: pulls GPS lat/lon (+ timestamp) out of the
    EXIF APP1 segment — the reference's ExifFileHandler role
    (geomesa-blobstore FileHandler SPI) without the metadata-extractor jar.
    Pure-Python TIFF/IFD walk; returns None when no GPS tags exist."""

    def can_handle(self, filename: str) -> bool:
        return filename.lower().endswith((".jpg", ".jpeg", ".tif", ".tiff"))

    def extract(self, filename: str, data: bytes):
        tiff = data if data[:2] in (b"II", b"MM") else _find_exif_tiff(data)
        if tiff is None:
            return None
        try:
            return _gps_from_tiff(tiff)
        except Exception:
            return None


def _find_exif_tiff(data: bytes):
    """Locate the TIFF blob inside a JPEG's APP1 Exif segment."""
    if data[:2] != b"\xff\xd8":
        return None
    pos = 2
    while pos + 4 <= len(data) and data[pos] == 0xFF:
        marker = data[pos + 1]
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            pos += 2
            continue
        (seglen,) = struct.unpack_from(">H", data, pos + 2)
        if marker == 0xE1 and data[pos + 4 : pos + 10] == b"Exif\x00\x00":
            return data[pos + 10 : pos + 2 + seglen]
        pos += 2 + seglen
    return None


def _gps_from_tiff(tiff: bytes):
    bo = "<" if tiff[:2] == b"II" else ">"

    def u16(o):
        return struct.unpack_from(bo + "H", tiff, o)[0]

    def u32(o):
        return struct.unpack_from(bo + "I", tiff, o)[0]

    def ifd_entries(off):
        n = u16(off)
        for i in range(n):
            e = off + 2 + 12 * i
            yield u16(e), u16(e + 2), u32(e + 4), e + 8

    def rationals(e_off, count):
        off = u32(e_off)
        return [
            u32(off + 8 * i) / max(1, u32(off + 8 * i + 4)) for i in range(count)
        ]

    gps_off = None
    for tag, _t, _c, val_off in ifd_entries(u32(4)):
        if tag == 0x8825:  # GPS IFD pointer
            gps_off = u32(val_off)
    if gps_off is None:
        return None
    lat = lon = None
    lat_ref, lon_ref = "N", "E"
    date_str = None
    time_hms = None
    for tag, typ, cnt, val_off in ifd_entries(gps_off):
        if tag == 1 and cnt <= 4:  # GPSLatitudeRef ("N\0" inline)
            lat_ref = chr(tiff[val_off])
        elif tag == 3 and cnt <= 4:  # GPSLongitudeRef
            lon_ref = chr(tiff[val_off])
        elif tag == 2 and typ == 5 and cnt == 3:  # GPSLatitude d/m/s
            d, m, s = rationals(val_off, 3)
            lat = d + m / 60.0 + s / 3600.0
        elif tag == 4 and typ == 5 and cnt == 3:  # GPSLongitude
            d, m, s = rationals(val_off, 3)
            lon = d + m / 60.0 + s / 3600.0
        elif tag == 7 and typ == 5 and cnt == 3:  # GPSTimeStamp h/m/s (UTC)
            time_hms = rationals(val_off, 3)
        elif tag == 0x1D and typ == 2:  # GPSDateStamp "YYYY:MM:DD"
            off = u32(val_off) if cnt > 4 else val_off
            date_str = tiff[off : off + cnt].split(b"\x00")[0].decode("ascii", "replace")
    if lat is None or lon is None:
        return None
    if lat_ref.upper() == "S":
        lat = -lat
    if lon_ref.upper() == "W":
        lon = -lon
    t_ms = None
    if date_str is not None:
        try:
            from datetime import datetime, timezone

            dt = datetime.strptime(date_str, "%Y:%m:%d").replace(tzinfo=timezone.utc)
            t_ms = int(dt.timestamp() * 1000)
            if time_hms is not None:
                h, m, s = time_hms
                t_ms += int(((h * 60 + m) * 60 + s) * 1000)
        except ValueError:
            t_ms = None
    return float(lon), float(lat), t_ms, {"source": "exif"}


class BlobStore:
    def __init__(
        self,
        root: Optional[str] = None,
        store: Optional[TpuDataStore] = None,
        handlers: Optional[List[FileHandler]] = None,
    ):
        self.root = root
        if root:
            os.makedirs(root, exist_ok=True)
            # open-time scrub (the blob root lives outside any datastore
            # root, so the store-open scrub never walks it): sweep tmp
            # stragglers a crashed _write_blob left behind
            for f in os.listdir(root):
                if f.endswith(".tmp"):
                    cleanup_tmp(os.path.join(root, f))
        self._mem: Dict[str, bytes] = {}
        self.store = store or TpuDataStore()
        self.store.create_schema(parse_spec("blobs", _SPEC))
        self.handlers = handlers if handlers is not None else [GeoJsonFileHandler(), ExifFileHandler()]

    def _blob_id(self, data: bytes) -> str:
        return hashlib.blake2b(data, digest_size=16).hexdigest()

    def put(
        self,
        filename: str,
        data: bytes,
        x: Optional[float] = None,
        y: Optional[float] = None,
        t_ms: Optional[int] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Store a blob; coordinates come from args or a matching handler."""
        if x is None or y is None:
            for h in self.handlers:
                if h.can_handle(filename):
                    got = h.extract(filename, data)
                    if got is not None:
                        x, y, ht, hmeta = got
                        t_ms = t_ms if t_ms is not None else ht
                        metadata = metadata if metadata is not None else hmeta
                        break
        if x is None or y is None:
            raise ValueError(f"no location for blob {filename!r} (no handler matched)")
        blob_id = self._blob_id(data)
        if self.root:
            _BLOB_RETRY.call(self._write_blob, os.path.join(self.root, blob_id), data)
        else:
            self._mem[blob_id] = data
        with self.store.writer("blobs") as w:
            w.write(
                [filename, json.dumps(metadata or {}), t_ms, Point(x, y)],
                fid=blob_id,
            )
        return blob_id

    @staticmethod
    def _write_blob(path: str, data: bytes) -> None:
        # tmp + fsync-before-rename (integrity.durable_write): a crash
        # mid-write can never publish a torn blob under its final
        # (content-addressed) id; a failed attempt unlinks its tmp, a
        # crashed one is swept at the next BlobStore open
        with trace.span("fs.block_write", path=path, bytes=len(data)):
            deadline.check("fs.block_write")
            faults.fault_point("fs.block_write")
            durable_write(path, data)

    @staticmethod
    def _read_blob(path: str) -> bytes:
        with trace.span("fs.block_read", path=path):
            deadline.check("fs.block_read")
            faults.fault_point("fs.block_read")
            with open(path, "rb") as fh:
                return fh.read()

    def get(self, blob_id: str) -> Optional[bytes]:
        if self.root:
            path = os.path.join(self.root, blob_id)
            if os.path.exists(path):
                return _BLOB_RETRY.call(self._read_blob, path)
            return None
        return self._mem.get(blob_id)

    def delete(self, blob_id: str) -> None:
        if self.root:
            path = os.path.join(self.root, blob_id)
            if os.path.exists(path):
                os.remove(path)
        else:
            self._mem.pop(blob_id, None)
        self.store.delete_features("blobs", [blob_id])

    def query(self, cql: str = "INCLUDE") -> List[Dict[str, Any]]:
        """[{id, filename, x, y, dtg, metadata}] matching the CQL."""
        res = self.store.query("blobs", cql)
        out = []
        for i, fid in enumerate(res.fids):
            out.append(
                {
                    "id": str(fid),
                    "filename": res.columns["filename"][i],
                    "x": float(res.columns["geom__x"][i]),
                    "y": float(res.columns["geom__y"][i]),
                    "dtg": int(res.columns["dtg"][i]),
                    "metadata": json.loads(res.columns["meta"][i] or "{}"),
                }
            )
        return out
