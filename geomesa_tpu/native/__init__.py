"""Native (C++) host kernels, built lazily and bound via ctypes.

The reference keeps its planning hot loops in tight JVM code (sfcurve
bit-twiddling, SURVEY.md section 2.1); here they are C++ compiled on first
use with the baked-in g++ toolchain. Everything has a pure-Python fallback —
set GEOMESA_TPU_NO_NATIVE=1 to force it (and tests compare the two).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "zranges.cpp")
_SO = os.path.join(_DIR, "_zranges.so")
_SEEK_SRC = os.path.join(_DIR, "seekscan.cpp")
_SEEK_SO = os.path.join(_DIR, "_seekscan.so")

_lock = threading.Lock()
_lib = None
_tried = False
_seek_lib = None
_seek_tried = False


def _build_so(src: str, so: str) -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", so + ".tmp", src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(so + ".tmp", so)
        return True
    except Exception:
        return False


def _build() -> bool:
    return _build_so(_SRC, _SO)


def load():
    """The ctypes lib, building if needed; None when unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            stale = (not os.path.exists(_SO)) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_SO)
            fn = lib.geomesa_zranges
            fn.restype = ctypes.c_longlong
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_longlong,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32),  # skip_mins (nullable)
                ctypes.POINTER(ctypes.c_uint32),  # skip_maxs (nullable)
                ctypes.c_int,  # nskip
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_longlong,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def load_seek():
    """The seek-scan ctypes lib, building if needed; None when unavailable."""
    global _seek_lib, _seek_tried
    if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _seek_tried:
            return _seek_lib
        _seek_tried = True
        try:
            stale = (not os.path.exists(_SEEK_SO)) or (
                os.path.getmtime(_SEEK_SO) < os.path.getmtime(_SEEK_SRC)
            )
            if stale and not _build_so(_SEEK_SRC, _SEEK_SO):
                return None
            lib = ctypes.CDLL(_SEEK_SO)
            fn = lib.geomesa_seek_scan
            fn.restype = ctypes.c_longlong
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_double),  # x
                ctypes.POINTER(ctypes.c_double),  # y
                ctypes.POINTER(ctypes.c_int64),  # t (nullable)
                ctypes.POINTER(ctypes.c_int64),  # starts
                ctypes.POINTER(ctypes.c_int64),  # ends
                ctypes.POINTER(ctypes.c_uint8),  # covered
                ctypes.c_longlong,  # nruns
                ctypes.c_double,  # xmin
                ctypes.c_double,  # xmax
                ctypes.c_double,  # ymin
                ctypes.c_double,  # ymax
                ctypes.c_int64,  # tlo
                ctypes.c_int64,  # thi
                ctypes.POINTER(ctypes.c_int64),  # out_rows
                ctypes.c_longlong,  # cap
            ]
            _seek_lib = lib
        except Exception:
            _seek_lib = None
        return _seek_lib


def seek_scan_native(
    x: np.ndarray,
    y: np.ndarray,
    t,
    starts: np.ndarray,
    ends: np.ndarray,
    covered: np.ndarray,
    box,
    tlo,
    thi,
):
    """One-pass candidate-interval filter (see seekscan.cpp); returns the
    final row-index array, or None when the lib is unavailable.

    ``box`` = (xmin, ymin, xmax, ymax) inclusive; ``tlo``/``thi`` inclusive
    epoch ms (ignored when ``t`` is None)."""
    lib = load_seek()
    if lib is None:
        return None
    xs = np.ascontiguousarray(x, dtype=np.float64)
    ys = np.ascontiguousarray(y, dtype=np.float64)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    en = np.ascontiguousarray(ends, dtype=np.int64)
    cv = np.ascontiguousarray(covered, dtype=np.uint8)
    if t is not None:
        ts = np.ascontiguousarray(t, dtype=np.int64)
        t_p = ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        lo, hi = int(tlo), int(thi)
    else:
        t_p = ctypes.POINTER(ctypes.c_int64)()
        lo = hi = 0
    cap = int(np.maximum(en - st, 0).sum())
    out = np.empty(max(cap, 1), dtype=np.int64)
    n = lib.geomesa_seek_scan(
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ys.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        t_p,
        st.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        en.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cv.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(st),
        float(box[0]),
        float(box[2]),
        float(box[1]),
        float(box[3]),
        lo,
        hi,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cap,
    )
    if n < 0:
        return None  # cannot happen with an exact cap; fall back anyway
    return out[:n]


def zranges_native(
    mins,
    maxs,
    bits: int,
    dims: int,
    max_ranges: Optional[int],
    precision: int,
    skip_mins=None,
    skip_maxs=None,
):
    """Native decomposition; returns None when the lib is unavailable.

    Output matches curve.zorder.zranges: list of (lower, upper, contained).
    """
    lib = load()
    if lib is None:
        return None
    m = np.ascontiguousarray(np.asarray(mins, dtype=np.uint32).reshape(-1))
    x = np.ascontiguousarray(np.asarray(maxs, dtype=np.uint32).reshape(-1))
    nboxes = len(m) // dims
    null_u32 = ctypes.POINTER(ctypes.c_uint32)()
    if skip_mins is not None:
        sm = np.ascontiguousarray(np.asarray(skip_mins, dtype=np.uint32).reshape(-1))
        sx = np.ascontiguousarray(np.asarray(skip_maxs, dtype=np.uint32).reshape(-1))
        nskip = len(sm) // dims
        sm_p = sm.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
        sx_p = sx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
    else:
        nskip = -1  # legacy contained semantics
        sm_p = sx_p = null_u32
    cap = max(4 * (max_ranges or 0), 1 << 16)
    budget = -1 if max_ranges is None else int(max_ranges)
    while True:
        lo = np.empty(cap, dtype=np.uint64)
        hi = np.empty(cap, dtype=np.uint64)
        cont = np.empty(cap, dtype=np.uint8)
        n = lib.geomesa_zranges(
            m.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            nboxes,
            bits,
            dims,
            budget,
            precision,
            sm_p,
            sx_p,
            nskip,
            lo.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            hi.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            cont.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap,
        )
        if n >= 0:
            return [
                (int(lo[i]), int(hi[i]), bool(cont[i])) for i in range(n)
            ]
        cap = int(-n) + 16
