"""Native (C++) host kernels, built lazily and bound via ctypes.

The reference keeps its planning hot loops in tight JVM code (sfcurve
bit-twiddling, SURVEY.md section 2.1); here they are C++ compiled on first
use with the baked-in g++ toolchain. Everything has a pure-Python fallback —
set GEOMESA_TPU_NO_NATIVE=1 to force it (and tests compare the two).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "zranges.cpp")
_SO = os.path.join(_DIR, "_zranges.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO + ".tmp", _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(_SO + ".tmp", _SO)
        return True
    except Exception:
        return False


def load():
    """The ctypes lib, building if needed; None when unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            stale = (not os.path.exists(_SO)) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_SO)
            fn = lib.geomesa_zranges
            fn.restype = ctypes.c_longlong
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_longlong,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32),  # skip_mins (nullable)
                ctypes.POINTER(ctypes.c_uint32),  # skip_maxs (nullable)
                ctypes.c_int,  # nskip
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_longlong,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def zranges_native(
    mins,
    maxs,
    bits: int,
    dims: int,
    max_ranges: Optional[int],
    precision: int,
    skip_mins=None,
    skip_maxs=None,
):
    """Native decomposition; returns None when the lib is unavailable.

    Output matches curve.zorder.zranges: list of (lower, upper, contained).
    """
    lib = load()
    if lib is None:
        return None
    m = np.ascontiguousarray(np.asarray(mins, dtype=np.uint32).reshape(-1))
    x = np.ascontiguousarray(np.asarray(maxs, dtype=np.uint32).reshape(-1))
    nboxes = len(m) // dims
    null_u32 = ctypes.POINTER(ctypes.c_uint32)()
    if skip_mins is not None:
        sm = np.ascontiguousarray(np.asarray(skip_mins, dtype=np.uint32).reshape(-1))
        sx = np.ascontiguousarray(np.asarray(skip_maxs, dtype=np.uint32).reshape(-1))
        nskip = len(sm) // dims
        sm_p = sm.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
        sx_p = sx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
    else:
        nskip = -1  # legacy contained semantics
        sm_p = sx_p = null_u32
    cap = max(4 * (max_ranges or 0), 1 << 16)
    budget = -1 if max_ranges is None else int(max_ranges)
    while True:
        lo = np.empty(cap, dtype=np.uint64)
        hi = np.empty(cap, dtype=np.uint64)
        cont = np.empty(cap, dtype=np.uint8)
        n = lib.geomesa_zranges(
            m.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            nboxes,
            bits,
            dims,
            budget,
            precision,
            sm_p,
            sx_p,
            nskip,
            lo.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            hi.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            cont.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap,
        )
        if n >= 0:
            return [
                (int(lo[i]), int(hi[i]), bool(cont[i])) for i in range(n)
            ]
        cap = int(-n) + 16
