"""Native (C++) host kernels, built lazily and bound via ctypes.

The reference keeps its planning hot loops in tight JVM code (sfcurve
bit-twiddling, SURVEY.md section 2.1); here they are C++ compiled on first
use with the baked-in g++ toolchain. Everything has a pure-Python fallback —
set GEOMESA_TPU_NO_NATIVE=1 to force it (and tests compare the two).

Kernels:
  zranges.cpp   z2/z3 quad/oct-tree range decomposition (+ skip boxes)
  xzranges.cpp  XZ sequence-interval BFS (extent indices)
  seekscan.cpp  one-pass candidate-interval filter (the tserver hot loop)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()


def _host_tag() -> str:
    """Short fingerprint of this host's CPU (machine + ISA flags): cached
    .so files carry it in their name so a kernel built with -march=native
    on one host is never CDLL-loaded on a different CPU (SIGILL)."""
    import hashlib
    import platform

    sig = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    sig += line
                    break
    except OSError:
        pass
    return hashlib.md5(sig.encode()).hexdigest()[:10]


def _build_so(src: str, so: str) -> bool:
    # lazy JIT compile for THIS host (the host tag in `so` keys the cache):
    # -march=native lets the seek-scan loop vectorize; retry plain -O2 only
    # for compile errors — a missing g++ or a timeout fails the same way
    for flags in (["-O3", "-march=native"], ["-O2"]):
        try:
            subprocess.run(
                ["g++", *flags, "-shared", "-fPIC", "-o", so + ".tmp", src],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(so + ".tmp", so)
            return True
        except subprocess.CalledProcessError:
            continue
        except Exception:
            return False
    return False


class _NativeLib:
    """One lazily-built, cached ctypes kernel: source path, symbol and
    signature in one place (the loader boilerplate used to be copied per
    kernel and drifted)."""

    def __init__(self, src: str, so: str, symbol: str, restype, argtypes):
        self.src = os.path.join(_DIR, src)
        base, ext = os.path.splitext(so)
        self.so = os.path.join(_DIR, f"{base}.{_host_tag()}{ext}")
        self.symbol = symbol
        self.restype = restype
        self.argtypes = argtypes
        self._lib = None
        self._tried = False

    def load(self):
        if os.environ.get("GEOMESA_TPU_NO_NATIVE"):
            return None
        with _lock:
            if self._tried:
                return self._lib
            self._tried = True
            try:
                stale = (not os.path.exists(self.so)) or (
                    os.path.getmtime(self.so) < os.path.getmtime(self.src)
                )
                if stale and not _build_so(self.src, self.so):
                    return None
                lib = ctypes.CDLL(self.so)
                fn = getattr(lib, self.symbol)
                fn.restype = self.restype
                fn.argtypes = self.argtypes
                self._lib = lib
            except Exception:
                self._lib = None
            return self._lib


_c_u32p = ctypes.POINTER(ctypes.c_uint32)
_c_u64p = ctypes.POINTER(ctypes.c_uint64)
_c_u8p = ctypes.POINTER(ctypes.c_uint8)
_c_i64p = ctypes.POINTER(ctypes.c_int64)
_c_f64p = ctypes.POINTER(ctypes.c_double)

_ZRANGES = _NativeLib(
    "zranges.cpp",
    "_zranges.so",
    "geomesa_zranges",
    ctypes.c_longlong,
    [
        _c_u32p, _c_u32p,  # mins, maxs
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # nboxes, bits, dims
        ctypes.c_longlong, ctypes.c_int,  # max_ranges, precision
        _c_u32p, _c_u32p, ctypes.c_int,  # skip_mins, skip_maxs, nskip
        _c_u64p, _c_u64p, _c_u8p, ctypes.c_longlong,  # out lo/hi/cont, cap
    ],
)

_XZRANGES = _NativeLib(
    "xzranges.cpp",
    "_xzranges.so",
    "geomesa_xzranges",
    ctypes.c_longlong,
    [
        _c_f64p, _c_f64p,  # qmins, qmaxs (normalized)
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # nqueries, dims, g
        ctypes.c_longlong,  # max_ranges
        _c_i64p, _c_i64p, _c_u8p, ctypes.c_longlong,  # out lo/hi/cont, cap
    ],
)

_SEEKSCAN = _NativeLib(
    "seekscan.cpp",
    "_seekscan.so",
    "geomesa_seek_scan",
    ctypes.c_longlong,
    [
        _c_f64p, _c_f64p, _c_i64p,  # x, y, t (t nullable)
        _c_i64p, _c_i64p, _c_u8p, ctypes.c_longlong,  # starts, ends, covered, nruns
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,  # box
        ctypes.c_int64, ctypes.c_int64,  # tlo, thi
        _c_i64p, ctypes.c_longlong,  # out_rows, cap
    ],
)

_ENVSCAN = _NativeLib(
    "seekscan.cpp",
    "_seekscan.so",
    "geomesa_env_seek_scan",
    ctypes.c_longlong,
    [
        _c_f64p, _c_f64p, _c_f64p, _c_f64p,  # bxmin, bymin, bxmax, bymax
        _c_u8p,  # isrect flags (nullable)
        _c_i64p, _c_i64p, ctypes.c_longlong,  # starts, ends, nruns
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,  # query box
        ctypes.c_int,  # rect_query
        _c_i64p, _c_u8p, ctypes.c_longlong,  # out_rows, out_decided, cap
    ],
)


_BITDECODE = _NativeLib(
    "bitdecode.cpp",
    "_bitdecode.so",
    "bitmap_rows",
    ctypes.c_longlong,
    [_c_u8p, ctypes.c_longlong, ctypes.c_longlong, _c_i64p, ctypes.c_longlong],
)


def load_bitdecode():
    """The bitmap-decode ctypes lib; None when unavailable/disabled."""
    return _BITDECODE.load()


def bitmap_rows_native(bits, base: int, max_out: int):
    """Packed bitmap (np.packbits big bit order) -> int64 row indices
    (bit index + ``base``); None when the lib is unavailable. ``max_out``
    bounds the output (callers know the set-bit count from the wire
    header)."""
    import numpy as np

    lib = load_bitdecode()
    if lib is None:
        return None
    bits = np.ascontiguousarray(bits, dtype=np.uint8)
    out = np.empty(max_out, dtype=np.int64)
    k = lib.bitmap_rows(
        bits.ctypes.data_as(_c_u8p),
        ctypes.c_longlong(len(bits)),
        ctypes.c_longlong(base),
        out.ctypes.data_as(_c_i64p),
        ctypes.c_longlong(max_out),
    )
    if k < 0:
        # popcount exceeded max_out: the wire header and bitmap disagree.
        # Raise rather than return None — None means "lib unavailable"
        # and callers would silently fall through to the numpy decode,
        # masking a wire-format bug instead of surfacing it.
        raise ValueError(
            f"corrupt bitmap wire data: popcount exceeds header count "
            f"{max_out}"
        )
    return out[:k]


def load():
    """The zranges ctypes lib; None when unavailable/disabled."""
    return _ZRANGES.load()


def load_xz():
    """The XZ-ranges ctypes lib; None when unavailable/disabled."""
    return _XZRANGES.load()


def load_seek():
    """The seek-scan ctypes lib; None when unavailable/disabled."""
    return _SEEKSCAN.load()


def load_env_seek():
    """The extent (envelope) seek-scan lib; None when unavailable."""
    return _ENVSCAN.load()


def env_seek_scan_native(
    bxmin, bymin, bxmax, bymax, starts, ends, qenv, rect_query: bool,
    isrect=None,
):
    """Extent candidate filter (see seekscan.cpp geomesa_env_seek_scan);
    returns (rows, decided_bool) or None when the lib is unavailable.
    ``qenv`` = (xmin, ymin, xmax, ymax) of the query geometry's envelope.
    ``isrect``: optional uint8/bool flags — rows whose geometry IS its
    envelope rectangle are decided by the envelope test alone."""
    lib = load_env_seek()
    if lib is None:
        return None
    a = np.ascontiguousarray(bxmin, dtype=np.float64)
    b = np.ascontiguousarray(bymin, dtype=np.float64)
    c = np.ascontiguousarray(bxmax, dtype=np.float64)
    d = np.ascontiguousarray(bymax, dtype=np.float64)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    en = np.ascontiguousarray(ends, dtype=np.int64)
    if isrect is not None:
        ir = np.ascontiguousarray(isrect, dtype=np.uint8)
        ir_p = ir.ctypes.data_as(_c_u8p)
    else:
        ir_p = _c_u8p()
    cap = int(np.maximum(en - st, 0).sum())
    rows = np.empty(max(cap, 1), dtype=np.int64)
    dec = np.empty(max(cap, 1), dtype=np.uint8)
    n = lib.geomesa_env_seek_scan(
        a.ctypes.data_as(_c_f64p),
        b.ctypes.data_as(_c_f64p),
        c.ctypes.data_as(_c_f64p),
        d.ctypes.data_as(_c_f64p),
        ir_p,
        st.ctypes.data_as(_c_i64p),
        en.ctypes.data_as(_c_i64p),
        len(st),
        float(qenv[0]),
        float(qenv[1]),
        float(qenv[2]),
        float(qenv[3]),
        1 if rect_query else 0,
        rows.ctypes.data_as(_c_i64p),
        dec.ctypes.data_as(_c_u8p),
        cap,
    )
    if n < 0:
        return None  # cannot happen with an exact cap; fall back anyway
    return rows[:n], dec[:n].astype(bool)


def zranges_native(
    mins,
    maxs,
    bits: int,
    dims: int,
    max_ranges: Optional[int],
    precision: int,
    skip_mins=None,
    skip_maxs=None,
):
    """Native decomposition as (lower[], upper[], contained[]) uint64/uint64/
    bool arrays; None when the lib is unavailable. The array form skips
    per-range Python tuple construction on the planning hot path."""
    if dims < 1 or dims > 3:
        return None  # fall back rather than silently answering empty
    lib = load()
    if lib is None:
        return None
    m = np.ascontiguousarray(np.asarray(mins, dtype=np.uint32).reshape(-1))
    x = np.ascontiguousarray(np.asarray(maxs, dtype=np.uint32).reshape(-1))
    nboxes = len(m) // dims
    null_u32 = _c_u32p()
    if skip_mins is not None:
        sm = np.ascontiguousarray(np.asarray(skip_mins, dtype=np.uint32).reshape(-1))
        sx = np.ascontiguousarray(np.asarray(skip_maxs, dtype=np.uint32).reshape(-1))
        nskip = len(sm) // dims
        sm_p = sm.ctypes.data_as(_c_u32p)
        sx_p = sx.ctypes.data_as(_c_u32p)
    else:
        nskip = -1  # legacy contained semantics
        sm_p = sx_p = null_u32
    cap = max(4 * (max_ranges or 0), 1 << 16)
    # a NEGATIVE budget must not collide with the C++ 'unbounded' sentinel:
    # the Python walk treats it as an exhausted budget (clamp to 0)
    budget = -1 if max_ranges is None else max(0, int(max_ranges))
    while True:
        lo = np.empty(cap, dtype=np.uint64)
        hi = np.empty(cap, dtype=np.uint64)
        cont = np.empty(cap, dtype=np.uint8)
        n = lib.geomesa_zranges(
            m.ctypes.data_as(_c_u32p),
            x.ctypes.data_as(_c_u32p),
            nboxes,
            bits,
            dims,
            budget,
            precision,
            sm_p,
            sx_p,
            nskip,
            lo.ctypes.data_as(_c_u64p),
            hi.ctypes.data_as(_c_u64p),
            cont.ctypes.data_as(_c_u8p),
            cap,
        )
        if n >= 0:
            # copies: the views' base is the >=64K-entry scratch buffer, and
            # cached plans would otherwise retain ~1MB per query
            return lo[:n].copy(), hi[:n].copy(), cont[:n].astype(bool)
        cap = int(-n) + 16


def xzranges_native(qmins, qmaxs, dims: int, g: int, max_ranges: Optional[int]):
    """Native XZ BFS over normalized [0,1] windows; None when unavailable.
    Output matches _XZSFC.ranges_boxes: [(lower, upper, contained)]."""
    if dims < 2 or dims > 3 or g < 1 or g > 20:
        return None  # out of the kernel's domain: use the Python fallback
    lib = load_xz()
    if lib is None:
        return None
    m = np.ascontiguousarray(np.asarray(qmins, dtype=np.float64).reshape(-1))
    x = np.ascontiguousarray(np.asarray(qmaxs, dtype=np.float64).reshape(-1))
    nq = len(m) // dims
    budget = -1 if max_ranges is None else max(0, int(max_ranges))
    cap = max(4 * (max_ranges or 0) + (1 << dims) * (g + 1), 1 << 16)
    while True:
        lo = np.empty(cap, dtype=np.int64)
        hi = np.empty(cap, dtype=np.int64)
        cont = np.empty(cap, dtype=np.uint8)
        n = lib.geomesa_xzranges(
            m.ctypes.data_as(_c_f64p),
            x.ctypes.data_as(_c_f64p),
            nq,
            dims,
            g,
            budget,
            lo.ctypes.data_as(_c_i64p),
            hi.ctypes.data_as(_c_i64p),
            cont.ctypes.data_as(_c_u8p),
            cap,
        )
        if n >= 0:
            return [(int(lo[i]), int(hi[i]), bool(cont[i])) for i in range(n)]
        cap = int(-n) + 16


def seek_scan_native(
    x: np.ndarray,
    y: np.ndarray,
    t,
    starts: np.ndarray,
    ends: np.ndarray,
    covered: np.ndarray,
    box,
    tlo,
    thi,
):
    """One-pass candidate-interval filter (see seekscan.cpp); returns the
    final row-index array, or None when the lib is unavailable.

    ``box`` = (xmin, ymin, xmax, ymax) inclusive; ``tlo``/``thi`` inclusive
    epoch ms (ignored when ``t`` is None)."""
    lib = load_seek()
    if lib is None:
        return None
    xs = np.ascontiguousarray(x, dtype=np.float64)
    ys = np.ascontiguousarray(y, dtype=np.float64)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    en = np.ascontiguousarray(ends, dtype=np.int64)
    cv = np.ascontiguousarray(covered, dtype=np.uint8)
    if t is not None:
        ts = np.ascontiguousarray(t, dtype=np.int64)
        t_p = ts.ctypes.data_as(_c_i64p)
        lo, hi = int(tlo), int(thi)
    else:
        t_p = _c_i64p()
        lo = hi = 0
    cap = int(np.maximum(en - st, 0).sum())
    out = np.empty(max(cap, 1), dtype=np.int64)
    n = lib.geomesa_seek_scan(
        xs.ctypes.data_as(_c_f64p),
        ys.ctypes.data_as(_c_f64p),
        t_p,
        st.ctypes.data_as(_c_i64p),
        en.ctypes.data_as(_c_i64p),
        cv.ctypes.data_as(_c_u8p),
        len(st),
        float(box[0]),
        float(box[2]),
        float(box[1]),
        float(box[3]),
        lo,
        hi,
        out.ctypes.data_as(_c_i64p),
        cap,
    )
    if n < 0:
        return None  # cannot happen with an exact cap; fall back anyway
    return out[:n]
