// Native XZ range decomposition: BFS over the XZ quad/oct tree.
//
// C++ port of geomesa_tpu/curve/xz.py::_XZSFC.ranges_boxes (itself the
// rebuild of the reference's XZ2SFC.scala:146-252 sequence-interval BFS
// from the Boehm/Klump/Kriegel XZ-ordering paper). Planning for extent
// queries is latency-critical and the walk is data-dependent — host C++,
// like zranges.cpp. The Python implementation remains the tested oracle
// and the fallback; semantics (level-terminator queue, extended-element
// contains/overlap, lemma-3 intervals, budget flush, flag-aware merge)
// mirror it exactly.
//
// Build: g++ -O2 -shared -fPIC -o _xzranges.so xzranges.cpp

#include <cstdint>
#include <deque>
#include <vector>
#include <algorithm>

namespace {

struct Elem {
    double lo[3];
    double hi[3];
    double length;
};

struct Range {
    int64_t lo;
    int64_t hi;
    uint8_t contained;
};

// (base^(g-i) - 1) / (base - 1), precomputed per level
static void subtree_steps(int g, int base, int64_t* steps) {
    for (int i = 0; i <= g; ++i) {
        int64_t p = 1;
        for (int k = 0; k < g - i; ++k) p *= base;
        steps[i] = (p - 1) / (base - 1);
    }
}

// sequence code of the cell with lower-left `corner` at `level`
// (xz.py::_code_scalar / XZ2SFC.scala:264-286)
static int64_t code_scalar(const double* corner, int level, int dims, int g,
                           int base, const int64_t* steps) {
    double lo[3], hi[3];
    for (int d = 0; d < dims; ++d) {
        lo[d] = 0.0;
        hi[d] = 1.0;
    }
    int64_t cs = 0;
    for (int i = 0; i < level; ++i) {
        int q = 0;
        for (int d = 0; d < dims; ++d) {
            double center = (lo[d] + hi[d]) * 0.5;
            if (corner[d] >= center) q |= 1 << d;
        }
        cs += 1 + (int64_t)q * steps[i];
        for (int d = 0; d < dims; ++d) {
            double center = (lo[d] + hi[d]) * 0.5;
            if ((q >> d) & 1) lo[d] = center;
            else hi[d] = center;
        }
    }
    return cs;
}

}  // namespace

extern "C" {

// Decompose normalized [0,1]^dims query windows into XZ sequence-code
// ranges. Returns ranges written, or -needed when cap is insufficient.
//   qmins/qmaxs: [nqueries * dims] normalized window bounds
//   max_ranges: <0 = unbounded budget
long long geomesa_xzranges(
    const double* qmins, const double* qmaxs, int nqueries, int dims,
    int g, long long max_ranges,
    int64_t* out_lo, int64_t* out_hi, uint8_t* out_contained,
    long long cap) {
    if (nqueries <= 0 || dims < 2 || dims > 3 || g < 1 || g > 20) return 0;
    const int base = 1 << dims;
    int64_t steps[32];
    subtree_steps(g, base, steps);
    const long long stop =
        max_ranges >= 0 ? max_ranges : (long long)1 << 62;

    std::vector<Range> ranges;
    std::deque<Elem> queue;
    // children of the unit cube seed the queue at level 1
    {
        Elem root;
        for (int d = 0; d < dims; ++d) {
            root.lo[d] = 0.0;
            root.hi[d] = 1.0;
        }
        root.length = 1.0;
        for (int corner = 0; corner < base; ++corner) {
            Elem c;
            c.length = 0.5;
            for (int d = 0; d < dims; ++d) {
                double center = (root.lo[d] + root.hi[d]) * 0.5;
                if ((corner >> d) & 1) {
                    c.lo[d] = center;
                    c.hi[d] = root.hi[d];
                } else {
                    c.lo[d] = root.lo[d];
                    c.hi[d] = center;
                }
            }
            queue.push_back(c);
        }
    }
    const Elem TERMINATOR{{-1, -1, -1}, {-1, -1, -1}, -1.0};
    queue.push_back(TERMINATOR);
    int level = 1;
    while (level < g && !queue.empty() && (long long)ranges.size() < stop) {
        Elem e = queue.front();
        queue.pop_front();
        if (e.length < 0) {  // terminator
            if (!queue.empty()) {
                ++level;
                queue.push_back(TERMINATOR);
            }
            continue;
        }
        bool contained = false, over = false;
        for (int q = 0; q < nqueries && !contained; ++q) {
            bool c = true;
            for (int d = 0; d < dims; ++d) {
                if (!(qmins[q * dims + d] <= e.lo[d] &&
                      qmaxs[q * dims + d] >= e.hi[d] + e.length)) {
                    c = false;
                    break;
                }
            }
            if (c) contained = true;
        }
        if (!contained) {
            for (int q = 0; q < nqueries && !over; ++q) {
                bool o = true;
                for (int d = 0; d < dims; ++d) {
                    if (!(qmaxs[q * dims + d] >= e.lo[d] &&
                          qmins[q * dims + d] <= e.hi[d] + e.length)) {
                        o = false;
                        break;
                    }
                }
                if (o) over = true;
            }
        }
        if (contained) {
            int64_t mn = code_scalar(e.lo, level, dims, g, base, steps);
            ranges.push_back({mn, mn + steps[level - 1], 1});
        } else if (over) {
            int64_t mn = code_scalar(e.lo, level, dims, g, base, steps);
            ranges.push_back({mn, mn, 0});
            for (int corner = 0; corner < base; ++corner) {
                Elem c;
                c.length = e.length * 0.5;
                for (int d = 0; d < dims; ++d) {
                    double center = (e.lo[d] + e.hi[d]) * 0.5;
                    if ((corner >> d) & 1) {
                        c.lo[d] = center;
                        c.hi[d] = e.hi[d];
                    } else {
                        c.lo[d] = e.lo[d];
                        c.hi[d] = center;
                    }
                }
                queue.push_back(c);
            }
        }
    }
    // budget hit / max depth: flush remaining as loose subtree intervals
    while (!queue.empty()) {
        Elem e = queue.front();
        queue.pop_front();
        if (e.length < 0) {
            ++level;
            continue;
        }
        int64_t mn = code_scalar(e.lo, level, dims, g, base, steps);
        ranges.push_back({mn, mn + steps[level - 1], 0});
    }

    if (ranges.empty()) return 0;
    std::sort(ranges.begin(), ranges.end(), [](const Range& a, const Range& b) {
        return a.lo != b.lo ? a.lo < b.lo : a.hi < b.hi;
    });
    std::vector<Range> merged;
    merged.push_back(ranges[0]);
    for (size_t i = 1; i < ranges.size(); ++i) {
        Range& cur = merged.back();
        const Range& r = ranges[i];
        // mirror curve/zorder.py::merge_ranges: true overlaps always
        // coalesce (flag AND); adjacency only with equal flags
        if (r.lo <= cur.hi || (r.lo == cur.hi + 1 && r.contained == cur.contained)) {
            cur.hi = std::max(cur.hi, r.hi);
            cur.contained = cur.contained && r.contained;
        } else {
            merged.push_back(r);
        }
    }
    long long n = (long long)merged.size();
    if (n > cap) return -n;
    for (long long i = 0; i < n; ++i) {
        out_lo[i] = merged[i].lo;
        out_hi[i] = merged[i].hi;
        out_contained[i] = merged[i].contained;
    }
    return n;
}

}  // extern "C"
