// Bitmap -> row indices at memory speed: the host-side decode of the
// span-framed bitmap wire format (parallel/executor.py bitmap batch
// protocol). np.packbits bit order ("big"): bit (7-j) of byte i is row
// i*8 + j. Zero words (the common case outside hit clusters) skip 8
// bytes at a time. Role: the client-side decode of the tserver's
// returned key/value batch (reference BatchScanner consumption path);
// numpy's unpackbits+flatnonzero equivalent measured ~35 ms per 1 MB
// window vs ~1 ms here.
#include <cstdint>
#include <cstring>

namespace {

// per-byte decode table: bit positions (big bit order) + popcount —
// turns the inner loop branchless (one bounded copy per nonzero byte)
struct Tables {
    uint8_t pos[256][8];
    uint8_t cnt[256];
    Tables() {
        for (int b = 0; b < 256; ++b) {
            int k = 0;
            for (int j = 0; j < 8; ++j)
                if (b & (0x80 >> j)) pos[b][k++] = (uint8_t)j;
            cnt[b] = (uint8_t)k;
        }
    }
};
const Tables T;

inline long long decode_byte(uint8_t byte, long long row0, int64_t* out,
                             long long k) {
    int c = T.cnt[byte];
    const uint8_t* p = T.pos[byte];
    for (int t = 0; t < c; ++t) out[k + t] = row0 + p[t];
    return k + c;
}

}  // namespace

extern "C" {

// bits: nbytes packed bytes; out: row buffer of capacity ``cap``.
// Returns the number of set bits written (rows are base + bit index), or
// -1 if the popcount exceeds cap (header/bitmap mismatch — the caller
// must treat the buffer as corrupt, like every sibling kernel's cap).
long long bitmap_rows(const uint8_t* bits, long long nbytes, long long base,
                      int64_t* out, long long cap) {
    long long k = 0;
    long long i = 0;
    // word-skip over the zero runs; the 8-byte body writes at most 64
    // rows, so guard capacity per word and fall to the checked tail
    for (; i + 8 <= nbytes && k + 64 <= cap; i += 8) {
        uint64_t w;
        std::memcpy(&w, bits + i, 8);
        if (w == 0) continue;
        long long row0 = base + i * 8;
        for (int b = 0; b < 8; ++b)
            k = decode_byte(bits[i + b], row0 + b * 8, out, k);
    }
    for (; i < nbytes; ++i) {
        uint8_t byte = bits[i];
        if (!byte) continue;
        if (k + T.cnt[byte] > cap) return -1;
        k = decode_byte(byte, base + i * 8, out, k);
    }
    return k;
}

}  // extern "C"
