// Native z-range decomposition: quad/oct-tree BFS over Morton space.
//
// The C++ analog of the JVM sfcurve-zorder range decomposition the reference
// delegates to (called from Z2SFC.scala:52-53 / Z3SFC.scala:62). Planning is
// latency-critical and irregular (data-dependent BFS) — a poor fit for XLA —
// so it runs as native host code; semantics mirror
// geomesa_tpu/curve/zorder.py::zranges exactly (that Python version is the
// tested oracle and the fallback when no compiler is available).
//
// Build: g++ -O2 -shared -fPIC -o _zranges.so zranges.cpp

#include <cstdint>
#include <cstring>
#include <deque>
#include <vector>
#include <algorithm>

namespace {

struct Cell {
    uint32_t cmin[3];
    int level;
};

struct Range {
    uint64_t lo;
    uint64_t hi;
    uint8_t contained;
};

inline uint64_t interleave(const uint32_t* coords, int dims) {
    uint64_t z = 0;
    for (int d = 0; d < dims; ++d) {
        uint64_t c = coords[d];
        int k = 0;
        while (c) {
            if (c & 1) z |= 1ULL << (k * dims + d);
            c >>= 1;
            ++k;
        }
    }
    return z;
}

}  // namespace

extern "C" {

// Decompose boxes into z-ranges. Returns number of ranges written, or
// -needed if the output capacity was insufficient (caller retries).
//   mins/maxs: [nboxes * dims] per-dim inclusive bounds
//   max_ranges: <0 means unbounded
//   skip_mins/skip_maxs: [nskip * dims] optional INTERIOR boxes — when
//     nskip >= 0 (with non-null pointers) the output `contained` flag
//     means "cell inside some skip box" (every raw-domain value in the
//     cell provably satisfies the query's own predicate, so scans skip
//     the post-filter for these ranges); recursion still classifies
//     against the regular boxes. nskip == 0 therefore forces every flag
//     false (no interior). Pass nskip < 0 (null pointers) for the legacy
//     meaning (cell inside a regular box).
long long geomesa_zranges(
    const uint32_t* mins, const uint32_t* maxs, int nboxes,
    int bits, int dims, long long max_ranges, int precision,
    const uint32_t* skip_mins, const uint32_t* skip_maxs, int nskip,
    uint64_t* out_lo, uint64_t* out_hi, uint8_t* out_contained,
    long long cap) {
    if (nboxes <= 0 || dims < 1 || dims > 3) return 0;
    int max_level = std::min((long long)bits, std::max(1LL, (long long)(precision / dims)));

    std::vector<Range> ranges;
    std::deque<Cell> queue;
    Cell root;
    std::memset(root.cmin, 0, sizeof(root.cmin));
    root.level = 0;
    queue.push_back(root);

    while (!queue.empty()) {
        Cell cell = queue.front();
        queue.pop_front();
        uint64_t size = 1ULL << (bits - cell.level);
        bool contained = false, overlaps = false;
        for (int b = 0; b < nboxes && !contained; ++b) {
            bool cont = true, over = true;
            for (int d = 0; d < dims; ++d) {
                uint64_t c0 = cell.cmin[d];
                uint64_t c1 = c0 + size - 1;
                uint64_t lo = mins[b * dims + d];
                uint64_t hi = maxs[b * dims + d];
                if (!(lo <= c0 && c1 <= hi)) cont = false;
                if (!(lo <= c1 && c0 <= hi)) { over = false; break; }
            }
            if (over) overlaps = true;
            if (cont && over) contained = true;
        }
        if (!overlaps) continue;
        if (contained) {
            uint8_t flag = 1;
            if (nskip >= 0 && skip_mins != nullptr) {
                flag = 0;
                for (int b = 0; b < nskip && !flag; ++b) {
                    bool cont = true;
                    for (int d = 0; d < dims; ++d) {
                        uint64_t c0 = cell.cmin[d];
                        uint64_t c1 = c0 + size - 1;
                        if (!(skip_mins[b * dims + d] <= c0 &&
                              c1 <= skip_maxs[b * dims + d])) {
                            cont = false;
                            break;
                        }
                    }
                    if (cont) flag = 1;
                }
            }
            uint64_t zmin = interleave(cell.cmin, dims);
            uint64_t span = 1ULL << (dims * (bits - cell.level));
            ranges.push_back({zmin, zmin + span - 1, flag});
        } else if (cell.level >= max_level ||
                   (max_ranges >= 0 &&
                    (long long)(ranges.size() + queue.size()) >= max_ranges)) {
            uint64_t zmin = interleave(cell.cmin, dims);
            uint64_t span = 1ULL << (dims * (bits - cell.level));
            ranges.push_back({zmin, zmin + span - 1, 0});
        } else {
            uint32_t half = 1u << (bits - cell.level - 1);
            for (int corner = 0; corner < (1 << dims); ++corner) {
                Cell child;
                for (int d = 0; d < dims; ++d)
                    child.cmin[d] = cell.cmin[d] + (((corner >> d) & 1) ? half : 0);
                child.level = cell.level + 1;
                queue.push_back(child);
            }
        }
    }

    if (ranges.empty()) return 0;
    std::sort(ranges.begin(), ranges.end(), [](const Range& a, const Range& b) {
        return a.lo != b.lo ? a.lo < b.lo : a.hi < b.hi;
    });
    std::vector<Range> merged;
    merged.push_back(ranges[0]);
    for (size_t i = 1; i < ranges.size(); ++i) {
        Range& cur = merged.back();
        const Range& r = ranges[i];
        // truly overlapping ranges always coalesce (flag = AND); merely
        // adjacent ones only when flags match — a skip-eligible interior
        // run must not lose its flag to a neighboring boundary cell
        if (r.lo <= cur.hi || (r.lo == cur.hi + 1 && r.contained == cur.contained)) {
            cur.hi = std::max(cur.hi, r.hi);
            cur.contained = cur.contained && r.contained;
        } else {
            merged.push_back(r);
        }
    }
    long long n = (long long)merged.size();
    if (n > cap) return -n;
    for (long long i = 0; i < n; ++i) {
        out_lo[i] = merged[i].lo;
        out_hi[i] = merged[i].hi;
        out_contained[i] = merged[i].contained;
    }
    return n;
}

}  // extern "C"
