// Native seek-scan: candidate row intervals -> final filtered row indices.
//
// The host-side analog of the reference's tablet-server hot loop
// (Z3Iterator.seek/next + Z3Filter.inBounds, accumulo/iterators/
// Z3Iterator.scala:42-65): given the searchsorted candidate intervals of a
// selective plan and the raw f64/i64 columns, emit exactly the rows that
// satisfy the query's own bbox(+interval) predicate — one pass, no
// intermediate gathers. Rows in `covered` intervals (strict-interior
// z-ranges, see zranges.cpp skip boxes) are emitted without any test.
//
// Build: g++ -O2 -shared -fPIC -o _seekscan.so seekscan.cpp

#include <cstdint>

extern "C" {

// Returns rows written to out_rows, or -1 if cap was insufficient (caller
// retries with cap >= total candidate count).
//   x/y:      f64 coordinate columns (full block arrays, indexed by row)
//   t:        i64 epoch-ms column, or null when the predicate has no
//             temporal part
//   starts/ends: [nruns] candidate [start, end) row intervals
//   covered:  [nruns] flags — rows of covered intervals skip the test
//   box:      xmin, xmax, ymin, ymax inclusive f64 bounds
//   tlo/thi:  inclusive i64 ms bounds (caller folds exclusivity into +-1)
long long geomesa_seek_scan(
    const double* x, const double* y, const int64_t* t,
    const int64_t* starts, const int64_t* ends, const uint8_t* covered,
    long long nruns,
    double xmin, double xmax, double ymin, double ymax,
    int64_t tlo, int64_t thi,
    int64_t* out_rows, long long cap) {
    long long n = 0;
    for (long long r = 0; r < nruns; ++r) {
        int64_t s = starts[r];
        int64_t e = ends[r];
        if (e <= s) continue;
        if (covered[r]) {
            if (n + (e - s) > cap) return -1;
            for (int64_t i = s; i < e; ++i) out_rows[n++] = i;
            continue;
        }
        if (n + (e - s) > cap) return -1;  // worst case for this run
        if (t != nullptr) {
            for (int64_t i = s; i < e; ++i) {
                bool ok = x[i] >= xmin && x[i] <= xmax &&
                          y[i] >= ymin && y[i] <= ymax &&
                          t[i] >= tlo && t[i] <= thi;
                out_rows[n] = i;
                n += ok ? 1 : 0;  // branchless-ish compaction
            }
        } else {
            for (int64_t i = s; i < e; ++i) {
                bool ok = x[i] >= xmin && x[i] <= xmax &&
                          y[i] >= ymin && y[i] <= ymax;
                out_rows[n] = i;
                n += ok ? 1 : 0;
            }
        }
    }
    return n;
}

// Extent-feature (XZ) variant: candidate intervals + per-row ENVELOPE
// columns -> rows whose envelope overlaps the query box, with a parallel
// flag marking rows DECIDED by envelope math alone. For a rectangle query
// geometry, a feature envelope strictly inside the box implies intersects
// (decided=1); the all-zero placeholder envelope (null geometry) and
// boundary-straddling envelopes stay decided=0 — the caller runs the exact
// per-row geometry test only on those. Mirrors the vectorized prescreen in
// filter/evaluate.py::_eval_spatial, one pass, no intermediate gathers.
//
// Returns rows written, or -1 if cap insufficient (caller sizes exactly).
// isrect: optional (nullable) per-row flag marking features whose geometry
// IS its axis-aligned envelope rectangle — for a RECTANGLE query their
// envelope-overlap test is exact, so straddling rows skip the host's
// per-geometry ring test entirely.
long long geomesa_env_seek_scan(
    const double* bxmin, const double* bymin,
    const double* bxmax, const double* bymax,
    const uint8_t* isrect,
    const int64_t* starts, const int64_t* ends, long long nruns,
    double qxmin, double qymin, double qxmax, double qymax,
    int rect_query,
    int64_t* out_rows, uint8_t* out_decided, long long cap) {
    long long n = 0;
    for (long long r = 0; r < nruns; ++r) {
        int64_t s = starts[r];
        int64_t e = ends[r];
        if (e <= s) continue;
        if (n + (e - s) > cap) return -1;
        for (int64_t i = s; i < e; ++i) {
            bool overlap = bxmax[i] >= qxmin && bxmin[i] <= qxmax &&
                           bymax[i] >= qymin && bymin[i] <= qymax;
            if (!overlap) continue;
            bool placeholder = bxmin[i] == 0.0 && bymin[i] == 0.0 &&
                               bxmax[i] == 0.0 && bymax[i] == 0.0;
            bool decided = rect_query && !placeholder &&
                           ((bxmin[i] >= qxmin && bxmax[i] <= qxmax &&
                             bymin[i] >= qymin && bymax[i] <= qymax) ||
                            (isrect && isrect[i]));
            out_rows[n] = i;
            out_decided[n] = decided ? 1 : 0;
            ++n;
        }
    }
    return n;
}

}  // extern "C"
