"""Raster chip store + mosaicking (the geomesa-accumulo-raster analog).

Reference (geomesa-accumulo-raster data/AccumuloRasterStore.scala:37-170,
RasterQuery.scala, index/RasterIndexSchema.scala): chips are stored per
resolution under geohash-prefixed keys; a query picks the best available
resolution, scans the geohashes intersecting the bbox, and the WCS layer
mosaics returned chips into a coverage grid sized bounds/resolution.

TPU-first redesign: per-resolution chip sets keep VECTORIZED envelope
arrays (one (N,4) ndarray per resolution), so chip selection is a single
broadcast compare instead of a geohash range scan, and mosaicking is
array pasting with nearest-neighbor index math — ready to jit on device
when chips become HBM-resident.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.geom.base import Envelope


class Raster:
    """One chip: 2D (H, W) or 3D (H, W, bands) array + geographic bounds.

    resolution = degrees per pixel (x and y assumed square, like the
    reference's single lexicoded resolution). ``geohash`` is the chip's
    index key (RasterIndexSchema keys chips by geohash + lexicoded
    resolution); computed from the chip center when not supplied."""

    def __init__(self, data: np.ndarray, envelope: Envelope, raster_id: Optional[str] = None,
                 time_ms: int = 0, geohash: Optional[str] = None):
        self.data = np.asarray(data)
        self.envelope = envelope
        self.id = raster_id or f"r{id(self)}"
        self.time_ms = int(time_ms)
        if geohash is None:
            geohash = _containing_geohash(
                envelope, _gh_precision(self.resolution_of(data, envelope))
            )
        self.geohash = geohash

    @staticmethod
    def resolution_of(data: np.ndarray, envelope: Envelope) -> float:
        return (envelope.xmax - envelope.xmin) / data.shape[1]

    @property
    def resolution(self) -> float:
        return (self.envelope.xmax - self.envelope.xmin) / self.data.shape[1]


def _containing_geohash(envelope: Envelope, max_precision: int) -> str:
    """Longest geohash whose cell CONTAINS the envelope ("" = world).

    Containment keying is what makes the prefix scan route complete: two
    geohash cells intersect iff one's string prefixes the other, so a chip
    intersecting the query implies its (containing) cell intersects some
    decomposed query prefix — a center-keyed chip straddling a cell
    boundary would be silently dropped."""
    from geomesa_tpu.utils.geohash import decode_bounds, encode

    cx = (envelope.xmin + envelope.xmax) / 2.0
    cy = (envelope.ymin + envelope.ymax) / 2.0
    gh = str(encode(np.asarray([cx]), np.asarray([cy]), max_precision)[0])
    while gh:
        xmin, ymin, xmax, ymax = decode_bounds(gh)
        if (
            xmin <= envelope.xmin and xmax >= envelope.xmax
            and ymin <= envelope.ymin and ymax >= envelope.ymax
        ):
            return gh
        gh = gh[:-1]
    return ""


def _gh_precision(resolution: float) -> int:
    """Geohash precision whose cell size ~ matches a 256px chip at this
    resolution (coarser chips get shorter keys, like the reference's
    per-level geohash lengths)."""
    span = max(resolution * 256.0, 1e-9)
    p = 1
    # each geohash char ~ divides the cell by ~5.66 (sqrt(32)) on average
    cell = 45.0
    while cell > span and p < 9:
        cell /= 5.657
        p += 1
    return p


class RasterQuery:
    def __init__(self, envelope: Envelope, resolution: float):
        self.envelope = envelope
        self.resolution = float(resolution)


class RasterStore:
    """In-memory chip store, one vectorized index per stored resolution."""

    def __init__(self, name: str = "rasters"):
        self.name = name
        self._chips: Dict[float, List[Raster]] = {}
        # (N,4) materialized lazily per resolution (writes only append to
        # the chip list — rebuilding the array per insert would be O(N^2))
        self._envs: Dict[float, np.ndarray] = {}

    # -- writes --------------------------------------------------------------

    def put_raster(self, raster: Raster) -> None:
        res = _quantize(raster.resolution)
        self._chips.setdefault(res, []).append(raster)
        self._envs.pop(res, None)  # invalidate; rebuilt on next query

    def put_rasters(self, rasters: Sequence[Raster]) -> None:
        for r in rasters:
            self.put_raster(r)

    def _env_index(self, res: float) -> np.ndarray:
        envs = self._envs.get(res)
        if envs is None or len(envs) != len(self._chips[res]):
            envs = np.asarray([c.envelope.as_tuple() for c in self._chips[res]])
            self._envs[res] = envs
        return envs

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        """Persist every chip to ONE .npz (the durable-state edge the
        reference gets from its Accumulo raster tables): chip arrays
        under positional keys + a JSON manifest of (resolution,
        envelope, id) rows. Atomic via tmp + rename."""
        import json as _json
        import os as _os

        arrays: Dict[str, np.ndarray] = {}
        manifest = []
        i = 0
        for res in self.available_resolutions:
            for c in self._chips[res]:
                arrays[f"c{i}"] = c.data
                manifest.append([res, list(c.envelope.as_tuple()), c.id])
                i += 1
        arrays["manifest"] = np.frombuffer(
            _json.dumps({"name": self.name, "chips": manifest}).encode(),
            dtype=np.uint8,
        )
        tmp = f"{path}.{_os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez_compressed(f, **arrays)
            _os.replace(tmp, path)
        except BaseException:
            try:
                _os.remove(tmp)  # no orphaned multi-MB tmp on failure
            except OSError:
                pass
            raise

    @classmethod
    def load(cls, path: str) -> "RasterStore":
        import json as _json

        with np.load(path) as z:
            meta = _json.loads(bytes(z["manifest"].tobytes()).decode())
            store = cls(meta.get("name", "rasters"))
            for i, (res, env, rid) in enumerate(meta["chips"]):
                store.put_raster(
                    Raster(z[f"c{i}"], Envelope(*env), raster_id=rid)
                )
        return store

    # -- queries -------------------------------------------------------------

    @property
    def available_resolutions(self) -> List[float]:
        return sorted(self._chips)

    def _choose_resolution(self, wanted: float) -> Optional[float]:
        """Closest stored resolution by log-ratio (the suggestResolution
        analog, GeoMesaCoverageQueryParams)."""
        if not self._chips:
            return None
        res = np.asarray(self.available_resolutions)
        return float(res[np.argmin(np.abs(np.log(res / wanted)))])

    def get_rasters(self, query: RasterQuery) -> List[Raster]:
        res = self._choose_resolution(query.resolution)
        if res is None:
            return []
        e = self._env_index(res)
        q = query.envelope
        hit = (e[:, 2] >= q.xmin) & (e[:, 0] <= q.xmax) & (e[:, 3] >= q.ymin) & (e[:, 1] <= q.ymax)
        chips = self._chips[res]
        return [chips[i] for i in np.flatnonzero(hit)]

    def mosaic(self, query: RasterQuery, fill: float = 0.0) -> Tuple[np.ndarray, Envelope]:
        """Composite intersecting chips into one grid of
        ceil(bounds/resolution) pixels (AccumuloRasterStore.getGridCoverage
        sizing :155-170), nearest-neighbor resampled."""
        q = query.envelope
        width = max(1, int(math.ceil((q.xmax - q.xmin) / query.resolution)))
        height = max(1, int(math.ceil((q.ymax - q.ymin) / query.resolution)))
        chips = self.get_rasters(query)
        bands = () if not chips or chips[0].data.ndim == 2 else (chips[0].data.shape[2],)
        out = np.full((height, width) + bands, fill, dtype=np.float64)
        for chip in chips:
            _paste(out, chip, q, query.resolution)
        return out, q

    def delete_resolution(self, resolution: float) -> int:
        res = _quantize(resolution)
        n = len(self._chips.pop(res, []))
        self._envs.pop(res, None)
        return n

    # -- pyramid ingest (AccumuloRasterStore ingest + overview build) --------

    def ingest_raster(
        self,
        data: np.ndarray,
        envelope: Envelope,
        chip_size: int = 256,
        levels: Optional[int] = None,
        name: str = "r",
    ) -> Dict[float, int]:
        """Tile a full raster into geohash-keyed chips and build an
        overview PYRAMID by 2x box-filter downsampling per level until the
        whole raster fits one chip (the reference ingests pre-built
        pyramid levels from GeoServer; here the chain is built in-store).
        Returns {resolution: chips stored} per level."""
        data = np.asarray(data)
        out: Dict[float, int] = {}
        level = 0
        while True:
            out[_quantize(Raster.resolution_of(data, envelope))] = self._ingest_level(
                data, envelope, chip_size, f"{name}_L{level}"
            )
            h, w = data.shape[:2]
            done = (h <= chip_size and w <= chip_size) or (
                levels is not None and level + 1 >= levels
            )
            if done:
                break
            data, envelope = clip_and_downsample(data, envelope)
            level += 1
        return out

    def _ingest_level(
        self, data: np.ndarray, envelope: Envelope, chip_size: int, name: str
    ) -> int:
        h, w = data.shape[:2]
        res_x = (envelope.xmax - envelope.xmin) / w
        res_y = (envelope.ymax - envelope.ymin) / h
        n = 0
        for r0 in range(0, h, chip_size):
            for c0 in range(0, w, chip_size):
                r1 = min(r0 + chip_size, h)
                c1 = min(c0 + chip_size, w)
                # row 0 = north
                env = Envelope(
                    envelope.xmin + c0 * res_x,
                    envelope.ymax - r1 * res_y,
                    envelope.xmin + c1 * res_x,
                    envelope.ymax - r0 * res_y,
                )
                self.put_raster(
                    Raster(data[r0:r1, c0:c1], env, raster_id=f"{name}_{r0}_{c0}")
                )
                n += 1
        return n

    # -- geohash-keyed scan route (RasterIndexSchema parity) -----------------

    def geohash_index(self, resolution: float) -> Dict[str, List[Raster]]:
        """geohash -> chips at one stored resolution."""
        res = _quantize(resolution)
        out: Dict[str, List[Raster]] = {}
        for c in self._chips.get(res, []):
            out.setdefault(c.geohash, []).append(c)
        return out

    def get_rasters_by_geohash(self, query: RasterQuery) -> List[Raster]:
        """The reference's scan shape: decompose the query bbox into
        covering geohash prefixes and fetch chips under them, THEN exact-
        filter by envelope (prefix scans over-cover). Results match
        ``get_rasters`` (the vectorized fast path)."""
        res = self._choose_resolution(query.resolution)
        if res is None:
            return []
        idx = self.geohash_index(res)
        if not idx:
            return []
        plen = max(1, max(len(k) for k in idx))
        from geomesa_tpu.utils.geohash import decompose

        q = query.envelope
        prefixes = decompose(q.to_polygon(), max_hashes=64, max_precision=plen)
        out: List[Raster] = []
        for gh, chips in idx.items():
            # cells intersect iff one geohash prefixes the other; "" (world
            # cell, a chip too big for any cell) matches every prefix
            if any(gh.startswith(p) or p.startswith(gh) for p in prefixes):
                for c in chips:
                    e = c.envelope
                    if (
                        e.xmax >= q.xmin and e.xmin <= q.xmax
                        and e.ymax >= q.ymin and e.ymin <= q.ymax
                    ):
                        out.append(c)
        return out

    # -- WCS-style windowed read (GeoMesaCoverageReader analog) --------------

    def ingest_geotiff(
        self,
        path,
        chip_size: int = 256,
        levels: Optional[int] = None,
        name: str = "r",
        use_overviews: bool = False,
    ) -> Dict[float, int]:
        """Real-format ingest (VERDICT r3 #6): parse a GeoTIFF
        (raster_io.read_geotiff — strip/tile, none/deflate) and feed the
        pyramid chain. ``use_overviews`` ingests the file's OWN chained
        reduced-resolution IFD pages as pyramid levels instead of
        rebuilding the overview chain — exactly how the reference's
        coverage ingest consumes GeoServer-built pyramid levels
        (geomesa-accumulo-raster AccumuloRasterStore)."""
        from geomesa_tpu.raster_io import read_geotiff, read_geotiff_pages

        if use_overviews:
            # only the base page + genuine reduced-resolution pages
            # (NewSubfileType bit 0) become pyramid levels; mask or
            # unrelated pages are skipped. ``levels`` caps the count.
            pages = read_geotiff_pages(path, overviews_only=True)
            if levels is not None:
                pages = pages[: max(1, levels)]
            if any(env is None for _d, env in pages):
                raise ValueError(
                    "GeoTIFF page without georeferencing (ModelPixelScale "
                    "+ ModelTiepoint required on every ingested page)"
                )
            out: Dict[float, int] = {}
            for k, (data, env) in enumerate(pages):
                out.update(
                    self.ingest_raster(
                        data, env, chip_size=chip_size, levels=1,
                        name=f"{name}_p{k}",
                    )
                )
            return out
        data, env = read_geotiff(path)
        if env is None:
            raise ValueError(
                "GeoTIFF has no georeferencing (ModelPixelScale + "
                "ModelTiepoint required)"
            )
        return self.ingest_raster(
            data, env, chip_size=chip_size, levels=levels, name=name
        )

    def export_window_geotiff(
        self,
        path,
        envelope: Envelope,
        width: int,
        height: int,
        fill: float = 0.0,
        compress: bool = True,
    ) -> np.ndarray:
        """read_window -> GeoTIFF on disk (the WCS GetCoverage output
        format edge). Returns the window array that was written."""
        from geomesa_tpu.raster_io import write_geotiff

        window = self.read_window(envelope, width, height, fill=fill)
        write_geotiff(path, window, envelope, compress=compress)
        return window

    def read_window(
        self,
        envelope: Envelope,
        width: int,
        height: int,
        fill: float = 0.0,
    ) -> np.ndarray:
        """Read an arbitrary bbox at an arbitrary output size: resolution
        selection from the implied pixel size (suggestResolution), then a
        nearest-neighbor mosaic resampled to EXACTLY (height, width) — the
        WCS GetCoverage contract of GeoMesaCoverageReader."""
        # finest implied pixel size on either axis drives level selection
        # (a tall narrow window must not pick a level too coarse for y)
        res = min(
            (envelope.xmax - envelope.xmin) / max(width, 1),
            (envelope.ymax - envelope.ymin) / max(height, 1),
        )
        grid, _ = self.mosaic(RasterQuery(envelope, res), fill=fill)
        if grid.shape[:2] == (height, width):
            return grid
        # resample the mosaic grid to the requested window size
        src_h, src_w = grid.shape[:2]
        ry = np.clip(((np.arange(height) + 0.5) * src_h / height).astype(int), 0, src_h - 1)
        rx = np.clip(((np.arange(width) + 0.5) * src_w / width).astype(int), 0, src_w - 1)
        return grid[np.ix_(ry, rx)]


def clip_and_downsample(
    data: np.ndarray, envelope: Envelope
) -> Tuple[np.ndarray, Envelope]:
    """One overview step: clip odd edges (shrinking the envelope FIRST so
    the coarser level's pixels stay registered), 2x box-filter, and cast
    back to the source dtype — THE single home of the overview
    registration math (ingest_raster and the GeoTIFF writer both use
    it)."""
    h, w = data.shape[:2]
    h2, w2 = h // 2 * 2, w // 2 * 2
    if (h2, w2) != (h, w):
        res_x = (envelope.xmax - envelope.xmin) / w
        res_y = (envelope.ymax - envelope.ymin) / h
        envelope = Envelope(
            envelope.xmin,
            envelope.ymax - h2 * res_y,
            envelope.xmin + w2 * res_x,
            envelope.ymax,
        )
        data = data[:h2, :w2]
    # the box filter means in float; integer sources cast back so
    # overview pages keep the base page's storage type
    return _downsample2(data).astype(data.dtype, copy=False), envelope


def _downsample2(data: np.ndarray) -> np.ndarray:
    """2x box-filter downsample (overview chain step); odd edges clipped."""
    h, w = data.shape[:2]
    h2, w2 = h // 2 * 2, w // 2 * 2
    d = data[:h2, :w2]
    if d.ndim == 2:
        return d.reshape(h2 // 2, 2, w2 // 2, 2).mean(axis=(1, 3))
    return d.reshape(h2 // 2, 2, w2 // 2, 2, d.shape[2]).mean(axis=(1, 3))


def _quantize(res: float) -> float:
    return float(f"{res:.12g}")


def _paste(out: np.ndarray, chip: Raster, q: Envelope, resolution: float) -> None:
    """Nearest-neighbor paste of one chip into the output grid (row 0 =
    north, matching image conventions)."""
    h, w = out.shape[:2]
    # output pixel centers
    xs = q.xmin + (np.arange(w) + 0.5) * resolution
    ys = q.ymax - (np.arange(h) + 0.5) * resolution
    ce = chip.envelope
    ch, cw = chip.data.shape[:2]
    in_x = np.flatnonzero((xs >= ce.xmin) & (xs <= ce.xmax))
    in_y = np.flatnonzero((ys >= ce.ymin) & (ys <= ce.ymax))
    if not len(in_x) or not len(in_y):
        return
    src_x = np.clip(
        ((xs[in_x] - ce.xmin) / (ce.xmax - ce.xmin) * cw).astype(int), 0, cw - 1
    )
    src_y = np.clip(
        ((ce.ymax - ys[in_y]) / (ce.ymax - ce.ymin) * ch).astype(int), 0, ch - 1
    )
    out[np.ix_(in_y, in_x)] = chip.data[np.ix_(src_y, src_x)]
