"""Raster chip store + mosaicking (the geomesa-accumulo-raster analog).

Reference (geomesa-accumulo-raster data/AccumuloRasterStore.scala:37-170,
RasterQuery.scala, index/RasterIndexSchema.scala): chips are stored per
resolution under geohash-prefixed keys; a query picks the best available
resolution, scans the geohashes intersecting the bbox, and the WCS layer
mosaics returned chips into a coverage grid sized bounds/resolution.

TPU-first redesign: per-resolution chip sets keep VECTORIZED envelope
arrays (one (N,4) ndarray per resolution), so chip selection is a single
broadcast compare instead of a geohash range scan, and mosaicking is
array pasting with nearest-neighbor index math — ready to jit on device
when chips become HBM-resident.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.geom.base import Envelope


class Raster:
    """One chip: 2D (H, W) or 3D (H, W, bands) array + geographic bounds.

    resolution = degrees per pixel (x and y assumed square, like the
    reference's single lexicoded resolution)."""

    def __init__(self, data: np.ndarray, envelope: Envelope, raster_id: Optional[str] = None,
                 time_ms: int = 0):
        self.data = np.asarray(data)
        self.envelope = envelope
        self.id = raster_id or f"r{id(self)}"
        self.time_ms = int(time_ms)

    @property
    def resolution(self) -> float:
        return (self.envelope.xmax - self.envelope.xmin) / self.data.shape[1]


class RasterQuery:
    def __init__(self, envelope: Envelope, resolution: float):
        self.envelope = envelope
        self.resolution = float(resolution)


class RasterStore:
    """In-memory chip store, one vectorized index per stored resolution."""

    def __init__(self, name: str = "rasters"):
        self.name = name
        self._chips: Dict[float, List[Raster]] = {}
        # (N,4) materialized lazily per resolution (writes only append to
        # the chip list — rebuilding the array per insert would be O(N^2))
        self._envs: Dict[float, np.ndarray] = {}

    # -- writes --------------------------------------------------------------

    def put_raster(self, raster: Raster) -> None:
        res = _quantize(raster.resolution)
        self._chips.setdefault(res, []).append(raster)
        self._envs.pop(res, None)  # invalidate; rebuilt on next query

    def put_rasters(self, rasters: Sequence[Raster]) -> None:
        for r in rasters:
            self.put_raster(r)

    def _env_index(self, res: float) -> np.ndarray:
        envs = self._envs.get(res)
        if envs is None or len(envs) != len(self._chips[res]):
            envs = np.asarray([c.envelope.as_tuple() for c in self._chips[res]])
            self._envs[res] = envs
        return envs

    # -- queries -------------------------------------------------------------

    @property
    def available_resolutions(self) -> List[float]:
        return sorted(self._chips)

    def _choose_resolution(self, wanted: float) -> Optional[float]:
        """Closest stored resolution by log-ratio (the suggestResolution
        analog, GeoMesaCoverageQueryParams)."""
        if not self._chips:
            return None
        res = np.asarray(self.available_resolutions)
        return float(res[np.argmin(np.abs(np.log(res / wanted)))])

    def get_rasters(self, query: RasterQuery) -> List[Raster]:
        res = self._choose_resolution(query.resolution)
        if res is None:
            return []
        e = self._env_index(res)
        q = query.envelope
        hit = (e[:, 2] >= q.xmin) & (e[:, 0] <= q.xmax) & (e[:, 3] >= q.ymin) & (e[:, 1] <= q.ymax)
        chips = self._chips[res]
        return [chips[i] for i in np.flatnonzero(hit)]

    def mosaic(self, query: RasterQuery, fill: float = 0.0) -> Tuple[np.ndarray, Envelope]:
        """Composite intersecting chips into one grid of
        ceil(bounds/resolution) pixels (AccumuloRasterStore.getGridCoverage
        sizing :155-170), nearest-neighbor resampled."""
        q = query.envelope
        width = max(1, int(math.ceil((q.xmax - q.xmin) / query.resolution)))
        height = max(1, int(math.ceil((q.ymax - q.ymin) / query.resolution)))
        chips = self.get_rasters(query)
        bands = () if not chips or chips[0].data.ndim == 2 else (chips[0].data.shape[2],)
        out = np.full((height, width) + bands, fill, dtype=np.float64)
        for chip in chips:
            _paste(out, chip, q, query.resolution)
        return out, q

    def delete_resolution(self, resolution: float) -> int:
        res = _quantize(resolution)
        n = len(self._chips.pop(res, []))
        self._envs.pop(res, None)
        return n


def _quantize(res: float) -> float:
    return float(f"{res:.12g}")


def _paste(out: np.ndarray, chip: Raster, q: Envelope, resolution: float) -> None:
    """Nearest-neighbor paste of one chip into the output grid (row 0 =
    north, matching image conventions)."""
    h, w = out.shape[:2]
    # output pixel centers
    xs = q.xmin + (np.arange(w) + 0.5) * resolution
    ys = q.ymax - (np.arange(h) + 0.5) * resolution
    ce = chip.envelope
    ch, cw = chip.data.shape[:2]
    in_x = np.flatnonzero((xs >= ce.xmin) & (xs <= ce.xmax))
    in_y = np.flatnonzero((ys >= ce.ymin) & (ys <= ce.ymax))
    if not len(in_x) or not len(in_y):
        return
    src_x = np.clip(
        ((xs[in_x] - ce.xmin) / (ce.xmax - ce.xmin) * cw).astype(int), 0, cw - 1
    )
    src_y = np.clip(
        ((ce.ymax - ys[in_y]) / (ce.ymax - ce.ymin) * ch).astype(int), 0, ch - 1
    )
    out[np.ix_(in_y, in_x)] = chip.data[np.ix_(src_y, src_x)]
