"""Unique attribute values over a query (UniqueProcess analog)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def unique_values(
    store, name: str, attribute: str, cql: str = "INCLUDE", sort_by_count: bool = True
) -> List[Tuple[object, int]]:
    result = store.query(name, cql)
    if len(result) == 0:
        return []
    col = result.columns[attribute]
    nulls = result.columns.get(attribute + "__null")
    if nulls is not None:
        col = col[~nulls]
    col = col[np.array([v is not None for v in col], dtype=bool)] if col.dtype.kind == "O" else col
    uniq, counts = np.unique(col, return_counts=True)
    pairs = [
        (v.item() if isinstance(v, np.generic) else v, int(c)) for v, c in zip(uniq, counts)
    ]
    if sort_by_count:
        pairs.sort(key=lambda vc: (-vc[1], str(vc[0])))
    return pairs
