"""Analytic processes over the datastore (the geomesa-process analogs).

Reference: geomesa-process (SURVEY.md section 2.5): KNearestNeighborSearch
(geohash-spiral expanding search, knn/KNNQuery.scala), ProximitySearch,
TubeSelect (spatio-temporal corridor, tube/TubeBuilder.scala), Unique,
Query. Here the expanding search rides the Z2/Z3 index through the normal
query planner, and the exact distance/corridor math is vectorized numpy
over the candidate sets the index returns.
"""

from geomesa_tpu.process.knn import knn_search
from geomesa_tpu.process.proximity import proximity_search
from geomesa_tpu.process.tube import tube_select
from geomesa_tpu.process.unique import unique_values
