"""Analytic WPS-process analogs that delegate to the query engine.

The remaining geomesa-process-vector entries (GeoMesaProcessFactory SPI):
each reference process wraps a capability this framework exposes through
query hints or the stats layer — these functions give them the same
process-level names so a WPS-shaped caller finds one-call equivalents.

  MinMaxProcess        -> min_max           (stats MinMax sketch / exact)
  StatsProcess         -> stats_process     (stats hint)
  SamplingProcess      -> sampling_process  (sampling hint)
  QueryProcess         -> query_process     (plain CQL query)
  DensityProcess       -> density_process   (density hint / device kernel)
  ArrowConversionProcess -> arrow_conversion (arrow hint)
  BinConversionProcess -> bin_conversion    (bin hint)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from geomesa_tpu.index.planner import Query


def query_process(store, name: str, cql: str = "INCLUDE"):
    """QueryProcess.scala: run a CQL query, return the result."""
    return store.query(name, cql)


def min_max(store, name: str, attribute: str, cql: str = "INCLUDE", exact: bool = False):
    """MinMaxProcess.scala: (min, max) of an attribute, from the write-time
    MinMax sketch when available (exact=False) else by scanning. Sketches
    observed EVERY row, so visibility-bearing and age-off types always scan
    (same guards as datastore.count — unreadable/expired rows must not leak
    into the bounds)."""
    ft = store.get_schema(name)
    if hasattr(store, "_files"):
        # lazy-capable fs store: blocks may not be resident — trust the
        # durable visibility marker ('false' written on vis-free inserts;
        # absent on legacy stores -> conservative scan), like fs count()
        has_vis = store.metadata.read(name, "geomesa.vis") != "false"
    else:
        table = next(iter(store._tables[name].values()), None)
        has_vis = table is not None and any(
            b.has_col("__vis__") for b in table.blocks
        )
    expiring = getattr(store, "_age_off_cutoff", lambda _ft: None)(ft) is not None
    if not exact and cql == "INCLUDE" and store.stats is not None and not has_vis and not expiring:
        sk = store.stats.stats_for(ft).get(f"minmax:{attribute}")
        if sk is not None and not sk.is_empty:
            return sk.min, sk.max
    res = store.query(name, cql)
    col = res.columns[attribute]
    nulls = res.columns.get(attribute + "__null")
    if nulls is not None:
        col = col[~nulls]
    if not len(col):
        return None, None
    return col.min(), col.max()


def stats_process(store, name: str, stat_spec: str, cql: str = "INCLUDE") -> Any:
    """StatsProcess.scala: evaluate a stat-spec string over query results."""
    q = Query.cql(cql)
    q.hints["stats"] = stat_spec
    res = store.query(name, q)
    return res.aggregate["stats"]


def sampling_process(store, name: str, n: int, cql: str = "INCLUDE"):
    """SamplingProcess.scala: thin features to at most ~n via the sampling
    hint (rate-based, like SamplingIterator)."""
    # an estimate suffices for an inherently-approximate rate (and avoids a
    # full scan just to size the second scan)
    total = max(1, store.count(name, cql, exact=False))
    q = Query.cql(cql)
    q.hints["sampling"] = min(1.0, n / total)
    return store.query(name, q)


def density_process(
    store, name: str, envelope, width: int, height: int, cql: str = "INCLUDE"
) -> np.ndarray:
    """DensityProcess.scala: heat-map grid via the density push-down."""
    q = Query.cql(cql)
    q.hints["density"] = {
        "envelope": envelope, "width": int(width), "height": int(height)
    }
    res = store.query(name, q)
    return res.aggregate["density"]


def arrow_conversion(store, name: str, cql: str = "INCLUDE", **spec) -> bytes:
    """ArrowConversionProcess.scala: results as an Arrow IPC stream."""
    q = Query.cql(cql)
    q.hints["arrow"] = dict(spec) if spec else {}
    res = store.query(name, q)
    return res.aggregate["arrow"]


def bin_conversion(store, name: str, cql: str = "INCLUDE", track: str = "id") -> bytes:
    """BinConversionProcess.scala: results as packed BIN records."""
    q = Query.cql(cql)
    q.hints["bin"] = {"track": track}
    res = store.query(name, q)
    recs = res.aggregate["bin"]
    return recs.tobytes() if hasattr(recs, "tobytes") else recs
