"""Tube select: spatio-temporal corridor search along a track.

Reference: TubeSelectProcess / tube/TubeBuilder.scala — an input track
(points + times) is buffered in space and time and features inside the
moving corridor are returned. The track is resampled to a max gap, each
sample contributes an index bbox + time window, and the exact test keeps a
feature when it is within the buffer of a sample whose time is within the
time buffer (the reference's "interpolated gap" builder).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.process.geodesy import degrees_boxes, haversine_m


def _resample(track, max_gap_m: float):
    """Insert interpolated samples so adjacent samples are <= max_gap_m apart."""
    out = [track[0]]
    for (x0, y0, t0), (x1, y1, t1) in zip(track, track[1:]):
        d = float(haversine_m(x0, y0, x1, y1))
        steps = max(1, int(np.ceil(d / max_gap_m)))
        for s in range(1, steps + 1):
            f = s / steps
            out.append((x0 + (x1 - x0) * f, y0 + (y1 - y0) * f, t0 + (t1 - t0) * f))
    return out


def tube_select(
    store,
    name: str,
    track: Sequence[Tuple[float, float, int]],
    buffer_m: float = 1000.0,
    time_buffer_ms: int = 600_000,
    cql: Optional[str] = None,
    max_gap_m: Optional[float] = None,
):
    """QueryResult of features inside the corridor around ``track``
    ([(lon, lat, t_ms)] ordered by time)."""
    from geomesa_tpu.store.blocks import take_rows
    from geomesa_tpu.store.datastore import QueryResult

    if not track:
        raise ValueError("empty track")
    ft = store.get_schema(name)
    geom = ft.default_geometry.name
    dtg = ft.default_date.name if ft.default_date else None
    samples = _resample(list(track), max_gap_m or max(buffer_m * 2, 1.0))

    # one covering query: union bbox + overall time window (the planner
    # decomposes it; per-sample precision comes from the exact pass below)
    xs = [s[0] for s in samples]
    ys = [s[1] for s in samples]
    boxes = [b for x, y in zip(xs, ys) for b in degrees_boxes(x, y, buffer_m)]
    xmin = min(b[0] for b in boxes)
    ymin = min(b[1] for b in boxes)
    xmax = max(b[2] for b in boxes)
    ymax = max(b[3] for b in boxes)
    q = f"bbox({geom}, {xmin!r}, {ymin!r}, {xmax!r}, {ymax!r})"
    if dtg is not None:
        t_lo = int(min(s[2] for s in samples)) - time_buffer_ms
        t_hi = int(max(s[2] for s in samples)) + time_buffer_ms
        lo = np.datetime64(t_lo, "ms").astype("datetime64[ms]").item().isoformat() + "Z"
        hi = np.datetime64(t_hi, "ms").astype("datetime64[ms]").item().isoformat() + "Z"
        q = f"{q} AND {dtg} BETWEEN '{lo}' AND '{hi}'"
    if cql:
        q = f"({q}) AND ({cql})"
    result = store.query(name, q)
    if len(result) == 0:
        return result

    fx = np.asarray(result.columns[geom + "__x"], dtype=np.float64)
    fy = np.asarray(result.columns[geom + "__y"], dtype=np.float64)
    keep = np.zeros(len(result), dtype=bool)
    st = np.asarray([s[2] for s in samples], dtype=np.float64)
    ft_ms = (
        np.asarray(result.columns[dtg], dtype=np.float64) if dtg is not None else None
    )
    # [N, M] distance against samples, chunked to bound memory
    chunk = max(1, 4_000_000 // max(len(samples), 1))
    for s0 in range(0, len(result), chunk):
        s1 = min(s0 + chunk, len(result))
        d = haversine_m(
            fx[s0:s1, None], fy[s0:s1, None], np.asarray(xs)[None, :], np.asarray(ys)[None, :]
        )
        ok = d <= buffer_m
        if ft_ms is not None:
            ok &= np.abs(ft_ms[s0:s1, None] - st[None, :]) <= time_buffer_ms
        keep[s0:s1] = ok.any(axis=1)
    return QueryResult(ft, take_rows(result.columns, np.flatnonzero(keep)), result.plan)
