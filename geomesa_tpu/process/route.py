"""Route search: features along a route, heading-matched.

The RouteSearchProcess analog (geomesa-process-vector query/
RouteSearchProcess.scala): finds features within a buffer (meters) of a
route LineString whose headings align with the route's local direction —
following the route, not just crossing it.

TPU-era redesign: the per-feature JTS distance/projection loop becomes one
vectorized (N points x S segments) matrix pass — point-to-segment distance
in a local equirectangular frame and per-segment forward azimuths computed
once for the whole batch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from geomesa_tpu.geom.base import LineString

_R = 6_371_008.8  # mean earth radius, meters


def _segment_bearings(coords: np.ndarray) -> np.ndarray:
    """Forward azimuth (degrees from north, clockwise) per segment."""
    lon1, lat1 = np.radians(coords[:-1, 0]), np.radians(coords[:-1, 1])
    lon2, lat2 = np.radians(coords[1:, 0]), np.radians(coords[1:, 1])
    dlon = lon2 - lon1
    x = np.sin(dlon) * np.cos(lat2)
    y = np.cos(lat1) * np.sin(lat2) - np.sin(lat1) * np.cos(lat2) * np.cos(dlon)
    return (np.degrees(np.arctan2(x, y)) + 360.0) % 360.0


def _point_segment_distances_m(
    px: np.ndarray, py: np.ndarray, coords: np.ndarray
) -> np.ndarray:
    """(N, S) meters from each point to each route segment, equirectangular
    local frame (exact enough inside realistic buffer sizes)."""
    lat0 = np.radians(np.mean(coords[:, 1]))
    kx = np.cos(lat0) * np.pi / 180.0 * _R
    ky = np.pi / 180.0 * _R
    ax, ay = coords[:-1, 0] * kx, coords[:-1, 1] * ky  # (S,)
    bx, by = coords[1:, 0] * kx, coords[1:, 1] * ky
    qx, qy = (px * kx)[:, None], (py * ky)[:, None]  # (N,1)
    dx, dy = (bx - ax)[None, :], (by - ay)[None, :]  # (1,S)
    len2 = dx * dx + dy * dy
    t = ((qx - ax[None, :]) * dx + (qy - ay[None, :]) * dy) / np.where(len2 == 0, 1, len2)
    t = np.clip(t, 0.0, 1.0)
    cx = ax[None, :] + t * dx
    cy = ay[None, :] + t * dy
    return np.hypot(qx - cx, qy - cy)


def match_route(
    px: np.ndarray,
    py: np.ndarray,
    headings: Optional[np.ndarray],
    route: LineString,
    buffer_m: float,
    heading_threshold: float,
    bidirectional: bool = False,
) -> np.ndarray:
    """Boolean mask of points within ``buffer_m`` of the route whose heading
    is within ``heading_threshold`` degrees of the nearest segment's azimuth
    (mod 180 when bidirectional)."""
    coords = np.asarray(route.coords, dtype=np.float64)
    if len(coords) < 2 or not len(px):
        return np.zeros(len(px), dtype=bool)
    d = _point_segment_distances_m(np.asarray(px, float), np.asarray(py, float), coords)
    nearest = np.argmin(d, axis=1)
    in_buffer = d[np.arange(len(px)), nearest] <= buffer_m
    if headings is None:
        return in_buffer
    bearings = _segment_bearings(coords)[nearest]
    diff = np.abs((np.asarray(headings, float) - bearings + 180.0) % 360.0 - 180.0)
    if bidirectional:
        diff = np.minimum(diff, 180.0 - diff)
    return in_buffer & (diff <= heading_threshold)


def route_search(
    store,
    name: str,
    routes: Sequence[LineString],
    buffer_m: float,
    heading_threshold: float,
    heading_attr: Optional[str] = None,
    cql: str = "INCLUDE",
    bidirectional: bool = False,
) -> List[str]:
    """Feature ids along any of the routes (store-level entry point)."""
    ft = store.get_schema(name)
    geom = ft.default_geometry.name
    res = store.query(name, cql)
    if len(res) == 0:
        return []
    px = res.columns[geom + "__x"]
    py = res.columns[geom + "__y"]
    headings = None
    if heading_attr is not None:
        headings = np.asarray(res.columns[heading_attr], dtype=np.float64)
        nulls = res.columns.get(heading_attr + "__null")
        if nulls is not None:
            # a feature without a heading cannot be route-following
            # (NaN fails every threshold compare)
            headings = np.where(nulls, np.nan, headings)
    mask = np.zeros(len(px), dtype=bool)
    for route in routes:
        mask |= match_route(
            px, py, headings, route, buffer_m, heading_threshold, bidirectional
        )
    return [str(f) for f in np.asarray(res.fids)[mask]]
