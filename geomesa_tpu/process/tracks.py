"""Track-oriented processes: Point2Point, TrackLabel, HashAttribute, Join.

Reference: geomesa-process Point2PointProcess (consecutive points per track
-> line segments), TrackLabelProcess (latest point per track for labeling),
HashAttributeProcess (stable hash column for styling), JoinProcess
(attribute join between two types).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def point2point(
    store,
    name: str,
    track_attr: str,
    cql: str = "INCLUDE",
    break_on_day: bool = False,
) -> List[Dict[str, Any]]:
    """Per-track consecutive point pairs -> segments
    [{track, coords[[x0,y0],[x1,y1]], t0, t1}], time-ordered."""
    ft = store.get_schema(name)
    geom = ft.default_geometry.name
    dtg = ft.default_date.name if ft.default_date else None
    res = store.query(name, cql)
    if len(res) == 0:
        return []
    tracks = res.columns[track_attr]
    x = res.columns[geom + "__x"]
    y = res.columns[geom + "__y"]
    t = res.columns[dtg] if dtg else np.zeros(len(res), dtype=np.int64)
    out: List[Dict[str, Any]] = []
    for v in np.unique(tracks):
        idx = np.flatnonzero(tracks == v)
        idx = idx[np.argsort(t[idx], kind="stable")]
        for a, b in zip(idx, idx[1:]):
            if break_on_day and (t[a] // 86400000) != (t[b] // 86400000):
                continue
            out.append(
                {
                    "track": v,
                    "coords": [[float(x[a]), float(y[a])], [float(x[b]), float(y[b])]],
                    "t0": int(t[a]),
                    "t1": int(t[b]),
                }
            )
    return out


def track_labels(
    store, name: str, track_attr: str, cql: str = "INCLUDE"
) -> List[Dict[str, Any]]:
    """Latest feature per track (TrackLabelProcess)."""
    ft = store.get_schema(name)
    geom = ft.default_geometry.name
    dtg = ft.default_date.name if ft.default_date else None
    res = store.query(name, cql)
    if len(res) == 0:
        return []
    tracks = res.columns[track_attr]
    t = res.columns[dtg] if dtg else np.zeros(len(res), dtype=np.int64)
    out = []
    for v in np.unique(tracks):
        idx = np.flatnonzero(tracks == v)
        last = idx[np.argmax(t[idx])]
        out.append(
            {
                "track": v,
                "fid": str(res.fids[last]),
                "x": float(res.columns[geom + "__x"][last]),
                "y": float(res.columns[geom + "__y"][last]),
                "t": int(t[last]),
            }
        )
    return out


def hash_attribute(values: np.ndarray, modulo: int) -> np.ndarray:
    """Stable per-value hash in [0, modulo) (HashAttributeProcess; used to
    color-code tracks client-side)."""
    import hashlib

    out = np.empty(len(values), dtype=np.int32)
    cache: Dict[Any, int] = {}
    for i, v in enumerate(values):
        h = cache.get(v)
        if h is None:
            h = int.from_bytes(
                hashlib.blake2b(str(v).encode(), digest_size=4).digest(), "little"
            ) % modulo
            cache[v] = h
        out[i] = h
    return out


def join(
    store,
    left: str,
    right: str,
    left_attr: str,
    right_attr: str,
    left_cql: str = "INCLUDE",
    right_cql: str = "INCLUDE",
) -> Dict[str, np.ndarray]:
    """Inner attribute join of two feature types (JoinProcess): returns
    columns of the left result extended with right columns (prefixed)."""
    lres = store.query(left, left_cql)
    rres = store.query(right, right_cql)
    lkey = lres.columns[left_attr]
    rkey = rres.columns[right_attr]
    rindex: Dict[Any, int] = {}
    for i, v in enumerate(rkey):
        rindex.setdefault(v, i)  # first match wins
    keep = []
    rrows = []
    for i, v in enumerate(lkey):
        j = rindex.get(v)
        if j is not None:
            keep.append(i)
            rrows.append(j)
    keep = np.asarray(keep, dtype=np.int64)
    rrows = np.asarray(rrows, dtype=np.int64)
    out = {k: v[keep] for k, v in lres.columns.items()}
    for k, v in rres.columns.items():
        if k == "__fid__":
            out[f"{right}.__fid__"] = v[rrows]
        else:
            out[f"{right}.{k}"] = v[rrows]
    return out
