"""Proximity search: features within a distance of any input point.

Reference: ProximitySearchProcess (geomesa-process) buffers the input
features and runs a DWITHIN; here each input point contributes a
conservative bbox for the index scan and the exact haversine test prunes
the candidates.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.process.geodesy import degrees_boxes, haversine_m


def proximity_search(
    store,
    name: str,
    points: Sequence[Tuple[float, float]],
    distance_m: float,
    cql: Optional[str] = None,
):
    """QueryResult of features within distance_m of ANY input point."""
    from geomesa_tpu.store.blocks import take_rows
    from geomesa_tpu.store.datastore import QueryResult

    ft = store.get_schema(name)
    geom = ft.default_geometry.name
    boxes = [b for x, y in points for b in degrees_boxes(x, y, distance_m)]
    parts = " OR ".join(
        f"bbox({geom}, {b[0]!r}, {b[1]!r}, {b[2]!r}, {b[3]!r})" for b in boxes
    )
    q = f"({parts})" if parts else "EXCLUDE"
    if cql:
        q = f"{q} AND ({cql})"
    result = store.query(name, q)
    if len(result) == 0:
        return result
    xs = result.columns[geom + "__x"]
    ys = result.columns[geom + "__y"]
    keep = np.zeros(len(result), dtype=bool)
    for x, y in points:
        keep |= haversine_m(xs, ys, x, y) <= distance_m
    return QueryResult(ft, take_rows(result.columns, np.flatnonzero(keep)), result.plan)
