"""k-nearest-neighbor search via expanding index queries.

Reference: KNearestNeighborSearchProcess (knn/KNNQuery.scala,
knn/GeoHashSpiral.scala) spirals outward over geohash cells until k features
are in hand and the k-th distance bounds the search. Here the spiral is an
expanding bbox over the Z2/Z3 index (doubling radius), with the same
termination: once >= k candidates are found, one final query at the k-th
distance guarantees no closer feature was missed.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.process.geodesy import degrees_box, haversine_m


def _bbox_cql(ft, box, extra: Optional[str]) -> str:
    geom = ft.default_geometry.name
    cql = f"bbox({geom}, {box[0]!r}, {box[1]!r}, {box[2]!r}, {box[3]!r})"
    if extra:
        cql = f"({cql}) AND ({extra})"
    return cql


def _distances(ft, result, x: float, y: float) -> np.ndarray:
    geom = ft.default_geometry.name
    return haversine_m(result.columns[geom + "__x"], result.columns[geom + "__y"], x, y)


def knn_search(
    store,
    name: str,
    x: float,
    y: float,
    k: int = 10,
    initial_radius_m: float = 1000.0,
    max_radius_m: float = 2_000_000.0,
    cql: Optional[str] = None,
) -> List[Tuple[str, float]]:
    """[(fid, distance_m)] of the k nearest features to (x, y), ascending."""
    ft = store.get_schema(name)
    radius = float(initial_radius_m)
    result = None
    while True:
        result = store.query(name, _bbox_cql(ft, degrees_box(x, y, radius), cql))
        if len(result) >= k or radius >= max_radius_m:
            break
        radius *= 2.0
    if len(result) == 0:
        return []
    d = _distances(ft, result, x, y)
    order = np.argsort(d, kind="stable")[:k]
    kth = float(d[order[-1]])
    # the bbox is not a circle: if the k-th distance exceeds the scanned
    # radius, a closer feature may sit in the circle's corners — requery at
    # the k-th distance to close the search (KNNQuery's final window)
    if kth > radius and radius < max_radius_m:
        result = store.query(name, _bbox_cql(ft, degrees_box(x, y, kth), cql))
        d = _distances(ft, result, x, y)
        order = np.argsort(d, kind="stable")[:k]
    fids = result.fids
    return [(str(fids[i]), float(d[i])) for i in order]
