"""k-nearest-neighbor search via expanding index queries.

Reference: KNearestNeighborSearchProcess (knn/KNNQuery.scala,
knn/GeoHashSpiral.scala) spirals outward over geohash cells until k features
are in hand and the k-th distance bounds the search. Here the spiral is an
expanding bbox over the Z2/Z3 index (doubling radius), with the same
termination: once >= k candidates are found, one final query at the k-th
distance guarantees no closer feature was missed.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.process.geodesy import degrees_boxes, haversine_m


def _bbox_cql(ft, boxes, extra: Optional[str]) -> str:
    geom = ft.default_geometry.name
    parts = [
        f"bbox({geom}, {b[0]!r}, {b[1]!r}, {b[2]!r}, {b[3]!r})" for b in boxes
    ]
    cql = parts[0] if len(parts) == 1 else "(" + " OR ".join(parts) + ")"
    if extra:
        cql = f"({cql}) AND ({extra})"
    return cql


def _distances(ft, result, x: float, y: float) -> np.ndarray:
    geom = ft.default_geometry.name
    return haversine_m(result.columns[geom + "__x"], result.columns[geom + "__y"], x, y)


def knn_search(
    store,
    name: str,
    x: float,
    y: float,
    k: int = 10,
    initial_radius_m: float = 1000.0,
    max_radius_m: float = 2_000_000.0,
    cql: Optional[str] = None,
) -> List[Tuple[str, float]]:
    """[(fid, distance_m)] of the k nearest features to (x, y), ascending.
    Features beyond ``max_radius_m`` are never returned — identical
    semantics on the device top-k and host expanding-bbox paths.

    ``last_knn_path()`` reports which path answered this THREAD's most
    recent call ("device-topk" | "host-bbox") — benches and tests
    consult it per call so a silent fallback can never report host time
    as a device number (thread-local: concurrent callers, e.g. the REST
    server's threads, cannot clobber each other's marker)."""
    from geomesa_tpu.parallel.mesh import device_tripped, trip_device

    _PATH_LOCAL.path = "host-bbox"
    ft = store.get_schema(name)
    if (
        cql is None
        and _device_knn_wanted()
        and not device_tripped(store.executor, "GEOMESA_KNN_DEVICE")
    ):
        try:
            direct = _device_knn(store, name, ft, x, y, k, max_radius_m)
        except Exception as e:  # noqa: BLE001 - device/tunnel failure
            # a dead tunnel or backend compile error must not kill the
            # search: the host expanding-bbox path answers identically
            # (round-4 silicon: the suite's kNN config died on a TPU
            # setup/compile Unavailable mid-batch with no fallback)
            trip_device(store.executor, "GEOMESA_KNN_DEVICE", "knn", e)
            direct = None
        if direct is not None:
            _PATH_LOCAL.path = "device-topk"
            return direct
    radius = float(initial_radius_m)
    result = None
    while True:
        result = store.query(name, _bbox_cql(ft, degrees_boxes(x, y, radius), cql))
        if len(result) >= k or radius >= max_radius_m:
            break
        radius *= 2.0
    if len(result) == 0:
        return []
    d = _distances(ft, result, x, y)
    order = np.argsort(d, kind="stable")[:k]
    kth = float(d[order[-1]])
    # the bbox is not a circle: if the k-th distance exceeds the scanned
    # radius, a closer feature may sit in the circle's corners — requery at
    # the k-th distance to close the search (KNNQuery's final window)
    if kth > radius and radius < max_radius_m:
        result = store.query(name, _bbox_cql(ft, degrees_boxes(x, y, kth), cql))
        d = _distances(ft, result, x, y)
        order = np.argsort(d, kind="stable")[:k]
    fids = result.fids
    return [
        (str(fids[i]), float(d[i])) for i in order if d[i] <= max_radius_m
    ]


def _device_knn_wanted() -> bool:
    """Cost choice: the one-pass device top-k ranks EVERY resident row —
    a bargain on a LOCAL accelerator, a full scan on the CPU backend where
    the expanding-bbox seek path touches only candidate cells. Over a
    high-latency device link (tunneled/remote chip) the per-dispatch
    round trip alone dwarfs the host seek's sub-ms answer, so auto
    declines there too (measured link_latency_ms, round-3 silicon
    session: ~80 ms/query device vs ~0.2 ms host on the axon tunnel).
    GEOMESA_KNN_DEVICE: auto | 1 | 0."""
    import os

    env = os.environ.get("GEOMESA_KNN_DEVICE", "auto")
    if env == "0":
        return False
    if env == "1":
        return True
    import jax

    if jax.default_backend() == "cpu":
        return False
    from geomesa_tpu.parallel.mesh import link_latency_ms

    return link_latency_ms() <= _LINK_BUDGET_MS


# auto device paths decline when one round trip costs more than this
_LINK_BUDGET_MS = 10.0

_PATH_LOCAL = threading.local()


def last_knn_path() -> str:
    """Which path answered this thread's most recent knn_search call
    ("device-topk" | "host-bbox"; "?" before any call)."""
    return getattr(_PATH_LOCAL, "path", "?")


def _device_knn(store, name: str, ft, x: float, y: float, k: int,
                max_radius_m: float = np.inf):
    """One-pass device top-k (executor.knn_candidates): every chip ranks
    its resident rows and returns k candidates; exact f64 re-rank here.
    None when the store has no device executor / no point index."""
    import time as _time

    from geomesa_tpu.utils import devstats

    t0 = _time.perf_counter()
    dev0 = devstats.receipt_snapshot()
    knn = getattr(store.executor, "knn_candidates", None)
    if knn is None:
        return None
    if getattr(store, "_age_off_cutoff", lambda _ft: None)(ft) is not None:
        return None  # expired rows are masked by the query path only
    # lazy stores (FsDataStore) may have partitions on disk only; kNN has
    # no pruning filter, so everything must be resident before ranking
    ensure = getattr(store, "_ensure_loaded", None)
    if ensure is not None:
        ensure(name, None)
    tables = store._tables.get(name, {})
    table = tables.get("z3") or tables.get("z2")
    if table is None or table.num_rows == 0:
        return None
    parts = knn(table, x, y, k)
    if parts is None:
        return None
    geom = ft.default_geometry.name
    fids: List[str] = []
    dists: List[np.ndarray] = []
    seen = set()
    for block, rows in parts:
        px = block.gather(geom + "__x", rows)
        py = block.gather(geom + "__y", rows)
        bf = block.gather("__fid__", rows)
        keep = [i for i, f in enumerate(bf) if f not in seen]
        seen.update(bf[keep])
        fids.extend(bf[keep])
        dists.append(haversine_m(px[keep], py[keep], x, y))
    out: List[Tuple[str, float]]
    if not fids:
        out = []
    else:
        d = np.concatenate(dists)
        order = np.argsort(d, kind="stable")[:k]
        # radius bound applied BEFORE auditing so hits == returned results
        out = [
            (str(fids[i]), float(d[i])) for i in order if d[i] <= max_radius_m
        ]
    # the fast path bypasses store.query, so it must audit itself — the
    # host fallback is audited per bbox query it issues
    if store.metrics is not None:
        store.metrics.inc("queries")
        store.metrics.update_timer("query.scan", _time.perf_counter() - t0)
    if store.audit_writer is not None:
        from geomesa_tpu.utils.audit import QueryEvent

        # the device-heaviest path must carry its cost receipt like any
        # store.query row (compiles + both transfer directions)
        receipt = devstats.receipt_since(dev0)
        store.audit_writer.write_event(
            QueryEvent(
                store=type(store).__name__,
                type_name=name,
                user=store.user,
                filter=f"KNN({x}, {y}, k={k})",
                hints={"knn": k},
                date_ms=int(_time.time() * 1000),
                planning_ms=0.0,
                scanning_ms=1000 * (_time.perf_counter() - t0),
                hits=len(out),
                scan_path="device-topk",
                recompiles=int(receipt["recompiles"]),
                h2d_bytes=int(receipt["h2d_bytes"]),
                d2h_bytes=int(receipt["d2h_bytes"]),
                pad_ratio=float(receipt["pad_ratio"]),
            )
        )
    return out
