"""Shared geodesic helpers for the process layer.

Haversine distance on the WGS84 mean sphere — the role the reference's
GeoHashUtils/VincentyModel math plays for KNN and proximity searches.
"""

from __future__ import annotations

import numpy as np

EARTH_RADIUS_M = 6371008.8


def haversine_m(lon1, lat1, lon2, lat2) -> np.ndarray:
    """Great-circle distance in meters; broadcasts over numpy inputs."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, dtype=np.float64)) for v in (lon1, lat1, lon2, lat2))
    dlon = lon2 - lon1
    dlat = lat2 - lat1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.minimum(1.0, np.sqrt(a)))


def degrees_boxes(x: float, y: float, radius_m: float):
    """Wrap-aware cap cover: one lon/lat box, or TWO when the cap crosses
    the antimeridian (the single-box form clamps at +-180 and silently
    drops the wrapped lune — fatal for kNN near the dateline)."""
    c = radius_m / EARTH_RADIUS_M
    dlat = float(np.degrees(c))
    lat_lo = max(-90.0, float(y) - dlat)
    lat_hi = min(90.0, float(y) + dlat)
    sin_ratio = float(np.sin(min(c, np.pi / 2)) / max(1e-9, np.cos(np.radians(y))))
    if lat_hi >= 90.0 or lat_lo <= -90.0 or sin_ratio >= 1.0:
        return [(-180.0, lat_lo, 180.0, lat_hi)]
    dlon = float(np.degrees(np.arcsin(sin_ratio)))
    lo, hi = float(x) - dlon, float(x) + dlon
    if lo >= -180.0 and hi <= 180.0:
        return [(lo, lat_lo, hi, lat_hi)]
    boxes = [(max(-180.0, lo), lat_lo, min(180.0, hi), lat_hi)]
    if lo < -180.0:
        boxes.append((lo + 360.0, lat_lo, 180.0, lat_hi))
    if hi > 180.0:
        boxes.append((-180.0, lat_lo, hi - 360.0, lat_hi))
    return boxes


def degrees_box(x: float, y: float, radius_m: float):
    """Conservative lon/lat bbox containing the radius_m circle around (x, y).

    The max longitudinal half-width of a spherical cap is
    asin(sin(c) / cos(lat)) with c the angular radius — NOT c / cos(lat),
    which under-covers at high latitude. If the cap reaches a pole every
    longitude is included.
    """
    c = radius_m / EARTH_RADIUS_M  # angular radius
    dlat = float(np.degrees(c))
    lat_lo = max(-90.0, float(y) - dlat)
    lat_hi = min(90.0, float(y) + dlat)
    sin_ratio = float(np.sin(min(c, np.pi / 2)) / max(1e-9, np.cos(np.radians(y))))
    if lat_hi >= 90.0 or lat_lo <= -90.0 or sin_ratio >= 1.0:
        return (-180.0, lat_lo, 180.0, lat_hi)
    dlon = float(np.degrees(np.arcsin(sin_ratio)))
    return (
        max(-180.0, float(x) - dlon),
        lat_lo,
        min(180.0, float(x) + dlon),
        lat_hi,
    )
