"""Shared geodesic helpers for the process layer.

Haversine distance on the WGS84 mean sphere — the role the reference's
GeoHashUtils/VincentyModel math plays for KNN and proximity searches.
"""

from __future__ import annotations

import numpy as np

EARTH_RADIUS_M = 6371008.8


def haversine_m(lon1, lat1, lon2, lat2) -> np.ndarray:
    """Great-circle distance in meters; broadcasts over numpy inputs."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, dtype=np.float64)) for v in (lon1, lat1, lon2, lat2))
    dlon = lon2 - lon1
    dlat = lat2 - lat1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.minimum(1.0, np.sqrt(a)))


def degrees_box(x: float, y: float, radius_m: float):
    """Conservative lon/lat bbox containing the radius_m circle around (x, y)."""
    dlat = float(np.degrees(radius_m / EARTH_RADIUS_M))
    cos = max(0.01, float(np.cos(np.radians(y))))
    dlon = dlat / cos
    return (
        max(-180.0, float(x) - dlon),
        max(-90.0, float(y) - dlat),
        min(180.0, float(x) + dlon),
        min(90.0, float(y) + dlat),
    )
