"""Legacy semi-normalized SFC variants for reading pre-1.3 index data.

Reference: curve/LegacyZ2SFC.scala:14-25 / LegacyZ3SFC.scala — identical bit
interleave but ceil-based SemiNormalized dimensions (NormalizedDimension.
scala:87-97), kept so old persisted keys decode. New keys never use these.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from geomesa_tpu.curve import binnedtime
from geomesa_tpu.curve.binnedtime import TimePeriod
from geomesa_tpu.curve.normalized import (
    SemiNormalizedLat,
    SemiNormalizedLon,
    SemiNormalizedTime,
)
from geomesa_tpu.curve.zorder import z2_decode, z2_encode, z3_decode, z3_encode


class LegacyZ2SFC:
    """31-bit semi-normalized 2D curve (LegacyZ2SFC.scala:14-25)."""

    def __init__(self):
        prec = (1 << 31) - 1
        self.lon = SemiNormalizedLon(prec)
        self.lat = SemiNormalizedLat(prec)

    def index(self, x, y) -> np.ndarray:
        return z2_encode(self.lon.normalize(x), self.lat.normalize(y))

    def invert(self, z) -> Tuple[np.ndarray, np.ndarray]:
        xi, yi = z2_decode(z)
        return self.lon.denormalize(xi), self.lat.denormalize(yi)


class LegacyZ3SFC:
    """21-bit semi-normalized 3D curve (LegacyZ3SFC.scala)."""

    _cache = {}

    def __init__(self, period: TimePeriod):
        prec = (1 << 21) - 1
        self.period = TimePeriod.parse(period)
        self.lon = SemiNormalizedLon(prec)
        self.lat = SemiNormalizedLat(prec)
        self.time = SemiNormalizedTime(prec, float(binnedtime.max_offset(self.period)))

    @classmethod
    def for_period(cls, period) -> "LegacyZ3SFC":
        period = TimePeriod.parse(period)
        if period not in cls._cache:
            cls._cache[period] = cls(period)
        return cls._cache[period]

    def index(self, x, y, t) -> np.ndarray:
        return z3_encode(
            self.lon.normalize(x), self.lat.normalize(y), self.time.normalize(t)
        )

    def invert(self, z) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        xi, yi, ti = z3_decode(z)
        return (
            self.lon.denormalize(xi),
            self.lat.denormalize(yi),
            self.time.denormalize(ti),
        )
