"""Z-order (Morton) bit interleaving and range decomposition.

This replaces the external ``org.locationtech.sfcurve:sfcurve-zorder`` library
the reference delegates to (imported at Z2SFC.scala:13 / Z3SFC.scala:14; range
decomposition called as ``Z2.zranges`` / ``Z3.zranges``). The reference keeps
this in tight JVM bit-twiddling code; here the encode/decode paths are
vectorized numpy uint64 magic-mask passes (the same ops become XLA int32-limb
kernels in ``geomesa_tpu.ops.zkernels`` for on-device use), and range
decomposition is an explicit quad/oct-tree BFS with a range budget.

Layouts:
  * Z2: 2 dims x <=31 bits, x in even bit positions, y odd -> 62-bit key.
  * Z3: 3 dims x <=21 bits, x at bit 3k, y at 3k+1, t at 3k+2 -> 63-bit key.
"""

from __future__ import annotations

from collections import deque
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

_U = np.uint64


class IndexRange(NamedTuple):
    """A contiguous inclusive range of key values.

    ``contained`` is True when every key in the range satisfies the query
    (no post-filter needed), mirroring sfcurve's IndexRange flag used by the
    reference's loose-bbox decisions.
    """

    lower: int
    upper: int
    contained: bool


# ---------------------------------------------------------------------------
# 2D interleave: 31 bits/dim -> 62-bit keys
# ---------------------------------------------------------------------------

def _split2(x: np.ndarray) -> np.ndarray:
    """Spread the low 31 bits of x to even bit positions (uint64)."""
    x = x.astype(np.uint64) & _U(0x7FFFFFFF)
    x = (x ^ (x << _U(16))) & _U(0x0000FFFF0000FFFF)
    x = (x ^ (x << _U(8))) & _U(0x00FF00FF00FF00FF)
    x = (x ^ (x << _U(4))) & _U(0x0F0F0F0F0F0F0F0F)
    x = (x ^ (x << _U(2))) & _U(0x3333333333333333)
    x = (x ^ (x << _U(1))) & _U(0x5555555555555555)
    return x


def _combine2(z: np.ndarray) -> np.ndarray:
    """Gather even bit positions of z into the low 31 bits."""
    z = z.astype(np.uint64) & _U(0x5555555555555555)
    z = (z ^ (z >> _U(1))) & _U(0x3333333333333333)
    z = (z ^ (z >> _U(2))) & _U(0x0F0F0F0F0F0F0F0F)
    z = (z ^ (z >> _U(4))) & _U(0x00FF00FF00FF00FF)
    z = (z ^ (z >> _U(8))) & _U(0x0000FFFF0000FFFF)
    z = (z ^ (z >> _U(16))) & _U(0x00000000FFFFFFFF)
    return z


def z2_encode(xi, yi) -> np.ndarray:
    """Interleave two <=31-bit int arrays into a 62-bit Morton key (int64)."""
    xi = np.atleast_1d(np.asarray(xi, dtype=np.int64))
    yi = np.atleast_1d(np.asarray(yi, dtype=np.int64))
    return (_split2(xi) | (_split2(yi) << _U(1))).astype(np.int64)


def z2_decode(z) -> Tuple[np.ndarray, np.ndarray]:
    z = np.atleast_1d(np.asarray(z, dtype=np.int64)).astype(np.uint64)
    return _combine2(z).astype(np.int64), _combine2(z >> _U(1)).astype(np.int64)


# ---------------------------------------------------------------------------
# 3D interleave: 21 bits/dim -> 63-bit keys
# ---------------------------------------------------------------------------

def _split3(x: np.ndarray) -> np.ndarray:
    """Spread the low 21 bits of x to every 3rd bit position (uint64)."""
    x = x.astype(np.uint64) & _U(0x1FFFFF)
    x = (x | (x << _U(32))) & _U(0x00001F00000000FFFF)
    x = (x | (x << _U(16))) & _U(0x001F0000FF0000FF)
    x = (x | (x << _U(8))) & _U(0x100F00F00F00F00F)
    x = (x | (x << _U(4))) & _U(0x10C30C30C30C30C3)
    x = (x | (x << _U(2))) & _U(0x1249249249249249)
    return x


def _combine3(z: np.ndarray) -> np.ndarray:
    z = z.astype(np.uint64) & _U(0x1249249249249249)
    z = (z ^ (z >> _U(2))) & _U(0x10C30C30C30C30C3)
    z = (z ^ (z >> _U(4))) & _U(0x100F00F00F00F00F)
    z = (z ^ (z >> _U(8))) & _U(0x001F0000FF0000FF)
    z = (z ^ (z >> _U(16))) & _U(0x00001F00000000FFFF)
    z = (z ^ (z >> _U(32))) & _U(0x1FFFFF)
    return z


def z3_encode(xi, yi, ti) -> np.ndarray:
    """Interleave three <=21-bit int arrays into a 63-bit Morton key (int64)."""
    xi = np.atleast_1d(np.asarray(xi, dtype=np.int64))
    yi = np.atleast_1d(np.asarray(yi, dtype=np.int64))
    ti = np.atleast_1d(np.asarray(ti, dtype=np.int64))
    return (_split3(xi) | (_split3(yi) << _U(1)) | (_split3(ti) << _U(2))).astype(np.int64)


def z3_decode(z) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    z = np.atleast_1d(np.asarray(z, dtype=np.int64)).astype(np.uint64)
    return (
        _combine3(z).astype(np.int64),
        _combine3(z >> _U(1)).astype(np.int64),
        _combine3(z >> _U(2)).astype(np.int64),
    )


# ---------------------------------------------------------------------------
# Range decomposition (quad/oct-tree BFS; the sfcurve ``zranges`` analog)
# ---------------------------------------------------------------------------

def _interleave_scalar(coords: Sequence[int], dims: int) -> int:
    """Scalar interleave of per-dim ints (bit k of dim d -> z bit k*dims+d)."""
    z = 0
    for d, c in enumerate(coords):
        c = int(c)
        k = 0
        while c:
            if c & 1:
                z |= 1 << (k * dims + d)
            c >>= 1
            k += 1
    return z


def merge_ranges(ranges: List[IndexRange]) -> List[IndexRange]:
    """Sort and merge ranges. Truly overlapping ranges always coalesce
    (flag = AND); merely adjacent ones only when flags match — a contained
    (skip-eligible) run must not lose its flag to a boundary neighbor."""
    if not ranges:
        return []
    ranges = sorted(ranges, key=lambda r: (r.lower, r.upper))
    merged: List[IndexRange] = []
    cur = ranges[0]
    for r in ranges[1:]:
        if r.lower <= cur.upper or (
            r.lower == cur.upper + 1 and r.contained == cur.contained
        ):
            cur = IndexRange(
                cur.lower, max(cur.upper, r.upper), cur.contained and r.contained
            )
        else:
            merged.append(cur)
            cur = r
    merged.append(cur)
    return merged


def zranges_arrays(
    mins,
    maxs,
    bits: int,
    dims: int,
    max_ranges: Optional[int] = None,
    precision: int = 64,
    skip_mins=None,
    skip_maxs=None,
):
    """Array-form decomposition (lower[], upper[], contained[]) via the C++
    BFS; None when the native lib is unavailable (callers fall back to the
    tuple-based Python walk in :func:`zranges`)."""
    try:
        from geomesa_tpu.native import zranges_native

        return zranges_native(
            mins, maxs, bits, dims, max_ranges, precision, skip_mins, skip_maxs
        )
    except Exception:
        return None


def zranges(
    mins: Sequence[Sequence[int]],
    maxs: Sequence[Sequence[int]],
    bits: int,
    dims: int,
    max_ranges: Optional[int] = None,
    precision: int = 64,
    skip_mins: Optional[Sequence[Sequence[int]]] = None,
    skip_maxs: Optional[Sequence[Sequence[int]]] = None,
) -> List[IndexRange]:
    """Decompose axis-aligned boxes (in normalized int space) into z-ranges.

    The analog of ``Z2.zranges`` / ``Z3.zranges`` in the sfcurve library the
    reference calls from Z2SFC.scala:52-53 and Z3SFC.scala:62. Performs a
    breadth-first quad/oct-tree walk: a tree cell fully contained in some box
    emits a "contained" range covering its whole z-extent; a partially
    overlapping cell subdivides; once the range budget is met, unresolved
    cells emit loose (not-contained) ranges. Adjacent/overlapping ranges are
    merged in a final sort pass.

    Args:
      mins/maxs: per-box arrays of per-dim inclusive int bounds, shape (B, dims)
      bits: bits per dimension of the curve
      dims: 2 or 3
      max_ranges: rough budget on emitted ranges (None = unbounded, matching
        sfcurve's getOrElse(Int.MaxValue); the planner passes its
        SCAN_RANGES_TARGET of 2000, QueryProperties.scala:18)
      precision: total z bits of resolution to recurse to (64 = full depth)
      skip_mins/skip_maxs: optional INTERIOR boxes. When given, the output
        ``contained`` flag means "cell inside some skip box": every raw
        value in the cell provably satisfies the query's own (f64/ms)
        predicate, so scans skip the post-filter for that range. Recursion
        still classifies against the regular boxes. Without skip boxes the
        flag keeps the legacy cell-in-box meaning.
    """
    arrays = zranges_arrays(
        mins, maxs, bits, dims, max_ranges, precision, skip_mins, skip_maxs
    )
    if arrays is not None:
        lo, hi, cont = arrays
        return [
            IndexRange(l, h, c)
            for l, h, c in zip(lo.tolist(), hi.tolist(), cont.tolist())
        ]

    boxes = [
        (tuple(int(v) for v in lo), tuple(int(v) for v in hi))
        for lo, hi in zip(mins, maxs)
    ]
    if not boxes:
        return []
    skips = (
        None
        if skip_mins is None
        else [
            (tuple(int(v) for v in lo), tuple(int(v) for v in hi))
            for lo, hi in zip(skip_mins, skip_maxs)
        ]
    )

    max_level = min(bits, max(1, precision // dims))

    ranges: List[IndexRange] = []
    # queue entries: per-dim cell minimum (ints at full resolution) + level
    queue: deque = deque()
    queue.append((tuple([0] * dims), 0))

    def cell_bounds(cmin: Tuple[int, ...], level: int):
        size = 1 << (bits - level)
        return [(c, c + size - 1) for c in cmin]

    def emit(cmin: Tuple[int, ...], level: int, contained: bool):
        if contained and skips is not None:
            size = 1 << (bits - level)
            contained = any(
                all(
                    lo[d] <= cmin[d] and cmin[d] + size - 1 <= hi[d]
                    for d in range(dims)
                )
                for lo, hi in skips
            )
        zmin = _interleave_scalar(cmin, dims)
        span = 1 << (dims * (bits - level))
        ranges.append(IndexRange(zmin, zmin + span - 1, contained))

    while queue:
        cmin, level = queue.popleft()
        bounds = cell_bounds(cmin, level)
        # classify the cell against the union of boxes
        contained = False
        overlaps = False
        for lo, hi in boxes:
            if all(lo[d] <= bounds[d][0] and bounds[d][1] <= hi[d] for d in range(dims)):
                contained = True
                overlaps = True
                break
            if all(lo[d] <= bounds[d][1] and bounds[d][0] <= hi[d] for d in range(dims)):
                overlaps = True
        if not overlaps:
            continue
        if contained:
            emit(cmin, level, True)
        elif level >= max_level or (
            max_ranges is not None and len(ranges) + len(queue) >= max_ranges
        ):
            emit(cmin, level, False)
        else:
            half = 1 << (bits - level - 1)
            for corner in range(1 << dims):
                child = tuple(
                    cmin[d] + (half if (corner >> d) & 1 else 0) for d in range(dims)
                )
                queue.append((child, level + 1))

    return merge_ranges(ranges)
