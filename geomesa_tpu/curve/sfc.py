"""Point space-filling curves: Z2 (lon/lat) and Z3 (lon/lat/time-offset).

Vectorized rebuilds of the reference's Z2SFC/Z3SFC (geomesa-z3
.../curve/Z2SFC.scala:15-54, Z3SFC.scala:23-77): ``index`` normalizes doubles
into bit space and interleaves; ``invert`` decodes to bin centers; ``ranges``
decomposes query boxes into key ranges via the quad/oct-tree walk in
:mod:`geomesa_tpu.curve.zorder`.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.curve import binnedtime
from geomesa_tpu.curve.binnedtime import TimePeriod
from geomesa_tpu.curve.normalized import NormalizedLat, NormalizedLon, NormalizedTime
from geomesa_tpu.curve.zorder import (
    IndexRange,
    zranges_arrays,
    z2_decode,
    z2_encode,
    z3_decode,
    z3_encode,
    zranges,
)


class Z2SFC:
    """2D point curve, 31 bits per dimension by default (Z2SFC.scala:15)."""

    def __init__(self, precision: int = 31):
        self.precision = precision
        self.lon = NormalizedLon(precision)
        self.lat = NormalizedLat(precision)

    def index(self, x, y, lenient: bool = False) -> np.ndarray:
        """Encode lon/lat arrays to 62-bit z values (Z2SFC.scala:28-43)."""
        x = np.atleast_1d(np.asarray(x, dtype=np.float64))
        y = np.atleast_1d(np.asarray(y, dtype=np.float64))
        if lenient:
            x = np.clip(x, self.lon.min, self.lon.max)
            y = np.clip(y, self.lat.min, self.lat.max)
        else:
            self._check_bounds(x, y)
        return z2_encode(self.lon.normalize(x), self.lat.normalize(y))

    def _check_bounds(self, x: np.ndarray, y: np.ndarray) -> None:
        # phrased as require(all in bounds) so NaN fails, matching the
        # reference's require() semantics (Z2SFC.scala:30-31)
        ok = (
            (x >= self.lon.min)
            & (x <= self.lon.max)
            & (y >= self.lat.min)
            & (y <= self.lat.max)
        )
        if not ok.all():
            raise ValueError(
                f"Value(s) out of bounds ([{self.lon.min},{self.lon.max}], "
                f"[{self.lat.min},{self.lat.max}])"
            )

    def invert(self, z) -> Tuple[np.ndarray, np.ndarray]:
        xi, yi = z2_decode(z)
        return self.lon.denormalize(xi), self.lat.denormalize(yi)

    def ranges(
        self,
        xy: Sequence[Tuple[float, float, float, float]],
        precision: int = 64,
        max_ranges: Optional[int] = None,
        exact_skip: bool = False,
    ) -> List[IndexRange]:
        """Decompose (xmin, ymin, xmax, ymax) boxes into z ranges (Z2SFC.scala:50-54).

        With ``exact_skip`` the ``contained`` flag of the returned ranges is
        computed against the strict INTERIOR of each box (normalized bounds
        shrunk one unit per side): because ``normalize`` is monotone, a row
        whose cell lies inside the interior provably satisfies the raw f64
        bbox predicate, so scans may skip the post-filter for those ranges.
        """
        args = self._range_inputs(xy, exact_skip)
        return zranges(*args[:2], self.precision, 2, max_ranges, precision,
                       skip_mins=args[2], skip_maxs=args[3])

    def ranges_arrays(
        self,
        xy: Sequence[Tuple[float, float, float, float]],
        precision: int = 64,
        max_ranges: Optional[int] = None,
        exact_skip: bool = False,
    ):
        """(lower[], upper[], contained[]) arrays via the C++ BFS, or None
        when the native lib is unavailable (callers use :meth:`ranges`)."""
        args = self._range_inputs(xy, exact_skip)
        return zranges_arrays(*args[:2], self.precision, 2, max_ranges, precision,
                              skip_mins=args[2], skip_maxs=args[3])

    def _range_inputs(self, xy, exact_skip: bool):
        mins, maxs = [], []
        skip_mins: List[List[int]] = []
        skip_maxs: List[List[int]] = []
        for xmin, ymin, xmax, ymax in xy:
            self._check_bounds(
                np.asarray([xmin, xmax], dtype=np.float64),
                np.asarray([ymin, ymax], dtype=np.float64),
            )
            nx0, ny0 = int(self.lon.normalize(xmin)[()]), int(self.lat.normalize(ymin)[()])
            nx1, ny1 = int(self.lon.normalize(xmax)[()]), int(self.lat.normalize(ymax)[()])
            mins.append([nx0, ny0])
            maxs.append([nx1, ny1])
            if exact_skip and nx0 + 1 <= nx1 - 1 and ny0 + 1 <= ny1 - 1:
                skip_mins.append([nx0 + 1, ny0 + 1])
                skip_maxs.append([nx1 - 1, ny1 - 1])
        return (
            mins,
            maxs,
            skip_mins if exact_skip else None,
            skip_maxs if exact_skip else None,
        )


class Z3SFC:
    """3D point+time curve, 21 bits per dimension (Z3SFC.scala:23-66).

    The time dimension normalizes the offset *within* a time bin; callers pair
    each z value with its 2-byte bin (see Z3IndexKeySpace).
    """

    _cache = {}

    def __init__(self, period: TimePeriod, precision: int = 21):
        if not (0 < precision < 22):
            raise ValueError("Precision (bits) per dimension must be in [1,21]")
        self.period = TimePeriod.parse(period)
        self.precision = precision
        self.lon = NormalizedLon(precision)
        self.lat = NormalizedLat(precision)
        self.time = NormalizedTime(precision, float(binnedtime.max_offset(self.period)))

    @classmethod
    def for_period(cls, period: TimePeriod) -> "Z3SFC":
        """Cached instance per period (Z3SFC.scala:69-77)."""
        period = TimePeriod.parse(period)
        if period not in cls._cache:
            cls._cache[period] = cls(period)
        return cls._cache[period]

    @property
    def whole_period(self) -> Tuple[int, int]:
        return (int(self.time.min), int(self.time.max))

    def index(self, x, y, t, lenient: bool = False) -> np.ndarray:
        """Encode lon/lat/time-offset arrays to 63-bit z values (Z3SFC.scala:33-48)."""
        x = np.atleast_1d(np.asarray(x, dtype=np.float64))
        y = np.atleast_1d(np.asarray(y, dtype=np.float64))
        t = np.atleast_1d(np.asarray(t, dtype=np.int64))
        if lenient:
            x = np.clip(x, self.lon.min, self.lon.max)
            y = np.clip(y, self.lat.min, self.lat.max)
            t = np.clip(t, int(self.time.min), int(self.time.max))
        else:
            self._check_bounds(x, y, t)
        return z3_encode(
            self.lon.normalize(x), self.lat.normalize(y), self.time.normalize(t)
        )

    def _check_bounds(self, x: np.ndarray, y: np.ndarray, t: np.ndarray) -> None:
        ok = (
            (x >= self.lon.min)
            & (x <= self.lon.max)
            & (y >= self.lat.min)
            & (y <= self.lat.max)
            & (t >= self.time.min)
            & (t <= self.time.max)
        )
        if not ok.all():
            raise ValueError(
                f"Value(s) out of bounds ([{self.lon.min},{self.lon.max}], "
                f"[{self.lat.min},{self.lat.max}], [{self.time.min},{self.time.max}])"
            )

    def invert(self, z) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        xi, yi, ti = z3_decode(z)
        return (
            self.lon.denormalize(xi),
            self.lat.denormalize(yi),
            self.time.denormalize(ti).astype(np.int64),
        )

    def ranges(
        self,
        xy: Sequence[Tuple[float, float, float, float]],
        t: Sequence[Tuple[int, int]],
        precision: int = 64,
        max_ranges: Optional[int] = None,
        exact_skip: bool = False,
    ) -> List[IndexRange]:
        """Decompose spatial boxes x time-offset windows into z ranges
        (Z3SFC.scala:56-65: the cross product of boxes and windows).

        ``exact_skip``: compute the ``contained`` flag against the strict
        interior of each (box, window) so flagged ranges provably satisfy
        the raw predicate (see Z2SFC.ranges). The time dimension shrinks by
        an extra ``ceil(bins/extent)`` units per side to absorb the
        offset-unit floor rounding between raw ms and stored offsets."""
        # one normalized unit per side guards the normalize() floor; the
        # extra margin guards the ms -> offset-unit floor when normalized
        # units are finer than offset units (e.g. week: 2^21 bins / 604800s)
        args = self._range_inputs(xy, t, exact_skip)
        return zranges(*args[:2], self.precision, 3, max_ranges, precision,
                       skip_mins=args[2], skip_maxs=args[3])

    def ranges_arrays(
        self,
        xy: Sequence[Tuple[float, float, float, float]],
        t: Sequence[Tuple[int, int]],
        precision: int = 64,
        max_ranges: Optional[int] = None,
        exact_skip: bool = False,
    ):
        """(lower[], upper[], contained[]) arrays via the C++ BFS, or None
        when the native lib is unavailable (callers use :meth:`ranges`)."""
        args = self._range_inputs(xy, t, exact_skip)
        return zranges_arrays(*args[:2], self.precision, 3, max_ranges, precision,
                              skip_mins=args[2], skip_maxs=args[3])

    def _range_inputs(self, xy, t, exact_skip: bool):
        # one normalized unit per side guards the normalize() floor; the
        # extra margin guards the ms -> offset-unit floor when normalized
        # units are finer than offset units (e.g. week: 2^21 bins / 604800s)
        t_margin = 1 + int(np.ceil(self.time.bins / (self.time.max - self.time.min)))
        mins, maxs = [], []
        skip_mins: List[List[int]] = []
        skip_maxs: List[List[int]] = []
        for xmin, ymin, xmax, ymax in xy:
            for tmin, tmax in t:
                self._check_bounds(
                    np.asarray([xmin, xmax], dtype=np.float64),
                    np.asarray([ymin, ymax], dtype=np.float64),
                    np.asarray([tmin, tmax], dtype=np.int64),
                )
                nx0 = int(self.lon.normalize(xmin)[()])
                ny0 = int(self.lat.normalize(ymin)[()])
                nt0 = int(self.time.normalize(tmin)[()])
                nx1 = int(self.lon.normalize(xmax)[()])
                ny1 = int(self.lat.normalize(ymax)[()])
                nt1 = int(self.time.normalize(tmax)[()])
                mins.append([nx0, ny0, nt0])
                maxs.append([nx1, ny1, nt1])
                if (
                    exact_skip
                    and nx0 + 1 <= nx1 - 1
                    and ny0 + 1 <= ny1 - 1
                    and nt0 + t_margin <= nt1 - t_margin
                ):
                    skip_mins.append([nx0 + 1, ny0 + 1, nt0 + t_margin])
                    skip_maxs.append([nx1 - 1, ny1 - 1, nt1 - t_margin])
        return (
            mins,
            maxs,
            skip_mins if exact_skip else None,
            skip_maxs if exact_skip else None,
        )
