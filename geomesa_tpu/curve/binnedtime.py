"""Binned time: a timestamp as (short period-bin, long offset-into-bin).

Semantics match the reference (geomesa-z3 .../curve/BinnedTime.scala:44-227):

  ==========  =====================  ==================  =====================
  period      bin                    offset unit         max date (exclusive)
  ==========  =====================  ==================  =====================
  day         days since epoch       milliseconds        epoch + 32768 days
  week        weeks since epoch      seconds             epoch + 32768 weeks
  month       calendar months since  seconds             epoch + 32768 months
  year        calendar years since   minutes             epoch + 32768 years
  ==========  =====================  ==================  =====================

``max_offset`` (the time dimension's normalization max) is *fixed* per period
(BinnedTime.scala:113-120): day -> 86400000 ms, week -> 604800 s,
month -> 31 days of seconds, year -> 52 weeks of minutes.

All conversions are vectorized over int64 epoch-millisecond arrays using
numpy datetime64 calendar math (numpy months/years since epoch coincide with
Joda ``monthsBetween``/``yearsBetween`` from the epoch because the epoch falls
on the first instant of its day/month/year).
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional, Tuple

import numpy as np


class TimePeriod(enum.Enum):
    """BinnedTime.scala:216-227."""

    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @classmethod
    def parse(cls, s: "TimePeriod | str") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return cls(str(s).strip().lower())


class BinnedTime(NamedTuple):
    bin: int
    offset: int


EPOCH_MS = 0

_DAY_MS = 86400000
_WEEK_MS = 7 * _DAY_MS

# BinnedTime.scala:113-120
_MAX_OFFSET = {
    TimePeriod.DAY: _DAY_MS,                      # millis in a day
    TimePeriod.WEEK: _WEEK_MS // 1000,            # seconds in a week
    TimePeriod.MONTH: (_DAY_MS // 1000) * 31,     # seconds in 31 days
    TimePeriod.YEAR: (_WEEK_MS // 60000) * 52,    # minutes in 52 weeks
}

_MAX_BIN = 32767  # Short.MaxValue


def max_offset(period: TimePeriod) -> int:
    return _MAX_OFFSET[TimePeriod.parse(period)]


def _bin_starts_ms(bins: np.ndarray, period: TimePeriod) -> np.ndarray:
    """Epoch millis of the first instant of each bin."""
    bins = np.asarray(bins, dtype=np.int64)
    if period is TimePeriod.DAY:
        return bins * _DAY_MS
    if period is TimePeriod.WEEK:
        return bins * _WEEK_MS
    if period is TimePeriod.MONTH:
        return bins.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    if period is TimePeriod.YEAR:
        return bins.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
    raise ValueError(period)


def max_date_ms(period: TimePeriod) -> int:
    """Exclusive max indexable date in epoch millis (BinnedTime.scala:57-61)."""
    period = TimePeriod.parse(period)
    return int(_bin_starts_ms(np.asarray([_MAX_BIN + 1]), period)[0])


def time_to_binned(
    ms, period: TimePeriod, lenient: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized (bin, offset) from epoch-millisecond timestamps.

    Reference: BinnedTime.scala:161-227 (toDayAndMillis etc.). Raises on
    out-of-bounds dates unless ``lenient``, which clamps (the analog of
    indexing with lenient=true at Z3SFC.scala:43-48).
    """
    period = TimePeriod.parse(period)
    ms = np.atleast_1d(np.asarray(ms, dtype=np.int64))
    hi = max_date_ms(period)
    if lenient:
        ms = np.clip(ms, 0, hi - 1)
    else:
        if ms.size and (ms.min() < 0 or ms.max() >= hi):
            raise ValueError(
                f"Date exceeds indexable range [0, {hi}) ms for period {period.value}"
            )
    if period is TimePeriod.DAY:
        bins = ms // _DAY_MS
        offsets = ms - bins * _DAY_MS
    elif period is TimePeriod.WEEK:
        bins = ms // _WEEK_MS
        offsets = (ms - bins * _WEEK_MS) // 1000
    elif period is TimePeriod.MONTH:
        months = ms.astype("datetime64[ms]").astype("datetime64[M]")
        bins = months.astype(np.int64)
        offsets = (ms - months.astype("datetime64[ms]").astype(np.int64)) // 1000
    else:  # YEAR
        years = ms.astype("datetime64[ms]").astype("datetime64[Y]")
        bins = years.astype(np.int64)
        offsets = (ms - years.astype("datetime64[ms]").astype(np.int64)) // 60000
    return bins.astype(np.int16), offsets.astype(np.int64)


def binned_to_time(bins, offsets, period: TimePeriod) -> np.ndarray:
    """Inverse of :func:`time_to_binned` -> epoch millis.

    Reference: BinnedTime.scala fromDayAndMillis / fromWeekAndSeconds /
    fromMonthAndSeconds / fromYearAndMinutes.
    """
    period = TimePeriod.parse(period)
    bins = np.atleast_1d(np.asarray(bins, dtype=np.int64))
    offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
    starts = _bin_starts_ms(bins, period)
    if period is TimePeriod.DAY:
        return starts + offsets
    if period is TimePeriod.WEEK or period is TimePeriod.MONTH:
        return starts + offsets * 1000
    return starts + offsets * 60000


def bounds_to_indexable_ms(
    lo: Optional[int], hi: Optional[int], period: TimePeriod
) -> Tuple[int, int]:
    """Clamp filter-extracted date bounds to the indexable domain.

    Reference: BinnedTime.boundsToIndexableDates (BinnedTime.scala:140-163) --
    missing bounds open to the domain edge; everything clamps into
    [epoch, maxDate - 1ms].
    """
    period = TimePeriod.parse(period)
    max_ms = max_date_ms(period) - 1
    lo_ms = EPOCH_MS if lo is None else min(max(int(lo), EPOCH_MS), max_ms)
    hi_ms = max_ms if hi is None else min(max(int(hi), EPOCH_MS), max_ms)
    return lo_ms, hi_ms
