"""L0 curve math: space-filling curves and time binning.

TPU-native rebuild of the reference's ``geomesa-z3`` module plus the external
``sfcurve-zorder`` library it delegates to (bit interleaving and range
decomposition; see SURVEY.md section 2.1). Everything here is vectorized
numpy operating on arrays of coordinates -- the hot ingest/planning path --
with device (JAX) variants living in ``geomesa_tpu.ops``.
"""

from geomesa_tpu.curve.normalized import (
    BitNormalizedDimension,
    NormalizedLat,
    NormalizedLon,
    NormalizedTime,
    SemiNormalizedDimension,
    SemiNormalizedLat,
    SemiNormalizedLon,
    SemiNormalizedTime,
)
from geomesa_tpu.curve.binnedtime import (
    BinnedTime,
    TimePeriod,
    EPOCH_MS,
    max_offset,
    max_date_ms,
    time_to_binned,
    binned_to_time,
    bounds_to_indexable_ms,
)
from geomesa_tpu.curve.zorder import (
    IndexRange,
    z2_encode,
    z2_decode,
    z3_encode,
    z3_decode,
    zranges,
)
from geomesa_tpu.curve.sfc import Z2SFC, Z3SFC
from geomesa_tpu.curve.xz import XZ2SFC, XZ3SFC, XZ_DEFAULT_G

__all__ = [
    "BitNormalizedDimension",
    "NormalizedLat",
    "NormalizedLon",
    "NormalizedTime",
    "SemiNormalizedDimension",
    "SemiNormalizedLat",
    "SemiNormalizedLon",
    "SemiNormalizedTime",
    "BinnedTime",
    "TimePeriod",
    "EPOCH_MS",
    "max_offset",
    "max_date_ms",
    "time_to_binned",
    "binned_to_time",
    "bounds_to_indexable_ms",
    "IndexRange",
    "z2_encode",
    "z2_decode",
    "z3_encode",
    "z3_decode",
    "zranges",
    "Z2SFC",
    "Z3SFC",
    "XZ2SFC",
    "XZ3SFC",
    "XZ_DEFAULT_G",
]
