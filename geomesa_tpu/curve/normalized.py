"""Dimension normalization: map doubles in a known range to ints in [0, 2^bits).

Semantics match the reference exactly (geomesa-z3 .../curve/NormalizedDimension.scala:57-97):

  * ``normalize(x) = maxIndex          if x >= max
                     floor((x - min) * bins / (max - min))  otherwise``
  * ``denormalize(i) = min + (min(i, maxIndex) + 0.5) * (max - min) / bins``  (bin centers)

All operations are vectorized over numpy arrays (float64 in, int64 out) so that
ingest-time key encoding is a single fused pass; IEEE-754 double math reproduces
the JVM's results bit-for-bit.
"""

from __future__ import annotations

import numpy as np


class BitNormalizedDimension:
    """Maps doubles in [min, max] to ints in [0, 2^precision).

    Reference: NormalizedDimension.scala:57-76 (BitNormalizedDimension).
    """

    def __init__(self, lo: float, hi: float, precision: int):
        if not (0 < precision < 32):
            raise ValueError("Precision (bits) must be in [1,31]")
        self.min = float(lo)
        self.max = float(hi)
        self.precision = precision
        self.bins = 1 << precision
        self._normalizer = self.bins / (self.max - self.min)
        self._denormalizer = (self.max - self.min) / self.bins
        self.max_index = self.bins - 1

    def normalize(self, x) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        scaled = np.floor((x - self.min) * self._normalizer)
        out = np.where(x >= self.max, float(self.max_index), scaled)
        # Non-finite / out-of-range inputs must cast DETERMINISTICALLY:
        # float->int64 casting of NaN/inf/overflow is implementation-
        # defined (INT64_MIN on x86, 0 on ARM), and covered-range
        # exact-skip soundness requires garbage rows to never land inside
        # a strict-interior skip box. NaN (null geometries under lenient
        # encoding) and -inf map to cell 0 (domain edge, always excluded
        # from skip boxes); +inf is already clamped by x >= max; huge
        # finite out-of-domain values saturate like the JVM's d.toLong.
        out = np.where(np.isnan(out), 0.0, out)
        out = np.clip(out, float(-(2**63)), float(2**63 - 2**10))
        return out.astype(np.int64)

    def denormalize(self, i) -> np.ndarray:
        i = np.asarray(i, dtype=np.int64)
        clamped = np.minimum(i, self.max_index).astype(np.float64)
        return self.min + (clamped + 0.5) * self._denormalizer

    def __eq__(self, other):
        return (
            isinstance(other, BitNormalizedDimension)
            and (self.min, self.max, self.precision) == (other.min, other.max, other.precision)
        )

    def __hash__(self):
        return hash((self.min, self.max, self.precision))

    def __repr__(self):
        return f"{type(self).__name__}({self.min}, {self.max}, bits={self.precision})"


class NormalizedLat(BitNormalizedDimension):
    """Latitude in [-90, 90] (NormalizedDimension.scala:78)."""

    def __init__(self, precision: int):
        super().__init__(-90.0, 90.0, precision)


class NormalizedLon(BitNormalizedDimension):
    """Longitude in [-180, 180] (NormalizedDimension.scala:80)."""

    def __init__(self, precision: int):
        super().__init__(-180.0, 180.0, precision)


class NormalizedTime(BitNormalizedDimension):
    """Time offset in [0, max] (NormalizedDimension.scala:82)."""

    def __init__(self, precision: int, hi: float):
        super().__init__(0.0, hi, precision)


class SemiNormalizedDimension:
    """Legacy ceil-based normalization kept for reading pre-1.3 index data.

    Reference: NormalizedDimension.scala:87-97 (SemiNormalizedDimension) --
    note it does not correctly bin the lower bound.
    """

    def __init__(self, lo: float, hi: float, precision: int):
        self.min = float(lo)
        self.max = float(hi)
        self.precision = precision
        self.max_index = precision

    def normalize(self, x) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        return np.ceil((x - self.min) / (self.max - self.min) * self.precision).astype(np.int64)

    def denormalize(self, i) -> np.ndarray:
        i = np.asarray(i, dtype=np.int64)
        out = (i.astype(np.float64) - 0.5) * (self.max - self.min) / self.precision + self.min
        return np.where(i == 0, self.min, out)


class SemiNormalizedLat(SemiNormalizedDimension):
    def __init__(self, precision: int):
        super().__init__(-90.0, 90.0, precision)


class SemiNormalizedLon(SemiNormalizedDimension):
    def __init__(self, precision: int):
        super().__init__(-180.0, 180.0, precision)


class SemiNormalizedTime(SemiNormalizedDimension):
    def __init__(self, precision: int, hi: float):
        super().__init__(0.0, hi, precision)
