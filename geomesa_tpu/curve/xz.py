"""XZ-ordering curves for geometries with extent (polygons/lines).

Rebuild of the reference's XZ2SFC/XZ3SFC (geomesa-z3 .../curve/XZ2SFC.scala,
XZ3SFC.scala), implementing 'XZ-Ordering: A Space-Filling Curve for Objects
with Spatial Extension' (Boehm, Klump, Kriegel). An object is indexed by an
*enlarged* quad/oct-tree cell chosen from its bounding box: the sequence-code
length is derived from the box's max extent (paper section 4.1), and the code
itself walks the tree accumulating subtree sizes (paper definition 2).

``index`` is vectorized over arrays of bounding boxes (ingest hot path);
``ranges`` is a host-side BFS over the tree with contained/overlap tests on
*extended* elements (each element's upper bounds stretched by its own width),
emitting lemma-3 sequence intervals for contained cells.
"""

from __future__ import annotations

import math
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.curve import binnedtime
from geomesa_tpu.curve.binnedtime import TimePeriod
from geomesa_tpu.curve.zorder import IndexRange, merge_ranges

# XZSFC.scala:11-16
XZ_DEFAULT_G = 12
_LOG_POINT_FIVE = math.log(0.5)


def _sequence_length(norm_mins, norm_maxs, g: int) -> np.ndarray:
    """Vectorized sequence-code length from normalized per-dim extents.

    Reference: XZ2SFC.scala:54-77 -- l1 = floor(log(maxDim)/log(0.5)); use
    l1+1 when the box fits in an enlarged cell at that finer resolution in
    every dimension, else l1; degenerate (zero-extent) boxes get g.
    """
    dims = len(norm_mins)
    max_dim = norm_maxs[0] - norm_mins[0]
    for d in range(1, dims):
        max_dim = np.maximum(max_dim, norm_maxs[d] - norm_mins[d])
    with np.errstate(divide="ignore"):
        l1 = np.floor(np.log(max_dim) / _LOG_POINT_FIVE)
    # maxDim == 0 -> log -> -inf -> l1 = +inf -> clamps to g
    l1 = np.where(np.isfinite(l1), l1, float(2**31 - 1))
    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        w2 = np.power(0.5, l1 + 1)
        fits = np.ones(max_dim.shape, dtype=bool)
        for d in range(dims):
            fits &= norm_maxs[d] <= (np.floor(norm_mins[d] / w2) * w2) + 2 * w2
    length = np.where(l1 >= g, g, np.where(fits, l1 + 1, l1))
    return length.astype(np.int64)


def _sequence_code(norm_mins, lengths: np.ndarray, g: int, base: int) -> np.ndarray:
    """Vectorized sequence code: walk ``length`` levels of the 2^dims-tree.

    Reference: XZ2SFC.scala:264-286 / XZ3SFC.scala:275-303. ``base`` is 4 for
    quads, 8 for octs; at step i the chosen child q adds
    1 + q*(base^(g-i)-1)/(base-1).
    """
    dims = len(norm_mins)
    n = norm_mins[0].shape[0]
    lo = [np.zeros(n, dtype=np.float64) for _ in range(dims)]
    hi = [np.ones(n, dtype=np.float64) for _ in range(dims)]
    cs = np.zeros(n, dtype=np.int64)
    for i in range(g):
        active = i < lengths
        if not active.any():
            break
        centers = [(lo[d] + hi[d]) / 2.0 for d in range(dims)]
        q = np.zeros(n, dtype=np.int64)
        for d in range(dims):
            q |= (norm_mins[d] >= centers[d]).astype(np.int64) << d
        step = (base ** (g - i) - 1) // (base - 1)
        cs = np.where(active, cs + 1 + q * step, cs)
        for d in range(dims):
            upper = (q >> d) & 1
            lo[d] = np.where(active & (upper == 1), centers[d], lo[d])
            hi[d] = np.where(active & (upper == 0), centers[d], hi[d])
    return cs


class _XZSFC:
    """Shared XZ logic over ``dims`` dimensions (base = 2^dims tree)."""

    def __init__(self, g: int, bounds: Sequence[Tuple[float, float]]):
        self.g = int(g)
        self.dims = len(bounds)
        self.base = 1 << self.dims
        self.bounds = [(float(lo), float(hi)) for lo, hi in bounds]
        self._sizes = [hi - lo for lo, hi in self.bounds]

    def _normalize(self, mins, maxs, lenient: bool):
        """Normalize user-space box corners to [0,1] (XZ2SFC.scala:330-369)."""
        nmins, nmaxs = [], []
        for d in range(self.dims):
            lo_b, hi_b = self.bounds[d]
            mn = np.atleast_1d(np.asarray(mins[d], dtype=np.float64))
            mx = np.atleast_1d(np.asarray(maxs[d], dtype=np.float64))
            # require() phrasing so NaN fails ordering/bounds (XZ2SFC.scala:335-341)
            if not np.all(mn <= mx):
                raise ValueError("Bounds must be ordered")
            if lenient:
                mn = np.clip(mn, lo_b, hi_b)
                mx = np.clip(mx, lo_b, hi_b)
            elif not np.all((mn >= lo_b) & (mx <= hi_b)):
                raise ValueError(
                    f"Values out of bounds [{lo_b} {hi_b}] in dim {d}"
                )
            nmins.append((mn - lo_b) / self._sizes[d])
            nmaxs.append((mx - lo_b) / self._sizes[d])
        return nmins, nmaxs

    def index_boxes(self, mins, maxs, lenient: bool = False) -> np.ndarray:
        """Vectorized sequence codes for arrays of bounding boxes."""
        nmins, nmaxs = self._normalize(mins, maxs, lenient)
        lengths = _sequence_length(nmins, nmaxs, self.g)
        return _sequence_code(nmins, lengths, self.g, self.base)

    def _code_scalar(self, corner: Tuple[float, ...], length: int) -> int:
        """Sequence code of the cell with lower-left ``corner`` (delegates to
        the vectorized walk so ingest and planning share one implementation)."""
        code = _sequence_code(
            [np.asarray([c], dtype=np.float64) for c in corner],
            np.asarray([length], dtype=np.int64),
            self.g,
            self.base,
        )
        return int(code[0])

    def ranges_boxes(
        self,
        windows: Sequence[Tuple[Tuple[float, ...], Tuple[float, ...]]],
        max_ranges: Optional[int] = None,
    ) -> List[IndexRange]:
        """BFS decomposition of OR'd query windows into sequence-code ranges.

        Reference: XZ2SFC.scala:146-252. Elements are *extended* (upper bounds
        + own width) for the contains/overlaps tests; a contained element
        emits the lemma-3 interval covering its whole subtree, a partial one
        emits its single code and recurses; when the budget is hit, remaining
        elements emit their full (loose) subtree intervals.
        """
        stop = max_ranges if max_ranges is not None else (1 << 31)
        queries = []
        for mins, maxs in windows:
            nmins, nmaxs = self._normalize(
                [np.asarray([m]) for m in mins], [np.asarray([m]) for m in maxs], False
            )
            queries.append(
                (
                    tuple(float(v[0]) for v in nmins),
                    tuple(float(v[0]) for v in nmaxs),
                )
            )

        # latency-critical planning path: prefer the C++ BFS (same
        # semantics, tested against this Python walk); fall back below
        try:
            from geomesa_tpu.native import xzranges_native

            native = xzranges_native(
                [q[0] for q in queries],
                [q[1] for q in queries],
                self.dims,
                self.g,
                max_ranges,
            )
            if native is not None:
                return [IndexRange(lo, hi, c) for lo, hi, c in native]
        except Exception:
            pass

        dims, base, g = self.dims, self.base, self.g
        ranges: List[IndexRange] = []

        def is_contained(lo, hi, length):
            for qlo, qhi in queries:
                if all(
                    qlo[d] <= lo[d] and qhi[d] >= hi[d] + length for d in range(dims)
                ):
                    return True
            return False

        def overlaps(lo, hi, length):
            for qlo, qhi in queries:
                if all(
                    qhi[d] >= lo[d] and qlo[d] <= hi[d] + length for d in range(dims)
                ):
                    return True
            return False

        def interval(lo, level, partial):
            mn = self._code_scalar(lo, level)
            if partial:
                return mn, mn
            return mn, mn + (base ** (g - level + 1) - 1) // (base - 1)

        def children(lo, hi, length):
            centers = [(lo[d] + hi[d]) / 2.0 for d in range(dims)]
            half = length / 2.0
            out = []
            for corner in range(base):
                clo = tuple(
                    centers[d] if (corner >> d) & 1 else lo[d] for d in range(dims)
                )
                chi = tuple(
                    hi[d] if (corner >> d) & 1 else centers[d] for d in range(dims)
                )
                out.append((clo, chi, half))
            return out

        TERMINATOR = None
        queue: deque = deque(
            children(tuple([0.0] * dims), tuple([1.0] * dims), 1.0)
        )
        queue.append(TERMINATOR)
        level = 1
        while level < g and queue and len(ranges) < stop:
            elem = queue.popleft()
            if elem is TERMINATOR:
                if queue:
                    level += 1
                    queue.append(TERMINATOR)
                continue
            lo, hi, length = elem
            if is_contained(lo, hi, length):
                mn, mx = interval(lo, level, partial=False)
                ranges.append(IndexRange(mn, mx, True))
            elif overlaps(lo, hi, length):
                mn, mx = interval(lo, level, partial=True)
                ranges.append(IndexRange(mn, mx, False))
                queue.extend(children(lo, hi, length))
        # flush whatever remains as loose full-subtree intervals
        while queue:
            elem = queue.popleft()
            if elem is TERMINATOR:
                level += 1
                continue
            lo, hi, length = elem
            mn, mx = interval(lo, level, partial=False)
            ranges.append(IndexRange(mn, mx, False))

        return merge_ranges(ranges)


class XZ2SFC(_XZSFC):
    """2D XZ curve over lon/lat (XZ2SFC.scala:25; default g=12)."""

    _cache = {}

    def __init__(
        self,
        g: int = XZ_DEFAULT_G,
        x_bounds: Tuple[float, float] = (-180.0, 180.0),
        y_bounds: Tuple[float, float] = (-90.0, 90.0),
    ):
        super().__init__(g, [x_bounds, y_bounds])

    @classmethod
    def for_g(cls, g: int = XZ_DEFAULT_G) -> "XZ2SFC":
        if g not in cls._cache:
            cls._cache[g] = cls(g)
        return cls._cache[g]

    def index(self, xmin, ymin, xmax, ymax, lenient: bool = False) -> np.ndarray:
        return self.index_boxes([xmin, ymin], [xmax, ymax], lenient)

    def ranges(
        self,
        queries: Sequence[Tuple[float, float, float, float]],
        max_ranges: Optional[int] = None,
    ) -> List[IndexRange]:
        windows = [((q[0], q[1]), (q[2], q[3])) for q in queries]
        return self.ranges_boxes(windows, max_ranges)


class XZ3SFC(_XZSFC):
    """3D XZ curve over lon/lat/time-offset, one instance per (g, period)
    (XZ3SFC.scala:26, 382-400)."""

    _cache = {}

    def __init__(
        self,
        g: int = XZ_DEFAULT_G,
        period: TimePeriod = TimePeriod.WEEK,
        x_bounds: Tuple[float, float] = (-180.0, 180.0),
        y_bounds: Tuple[float, float] = (-90.0, 90.0),
    ):
        self.period = TimePeriod.parse(period)
        z_max = float(binnedtime.max_offset(self.period))
        super().__init__(g, [x_bounds, y_bounds, (0.0, z_max)])

    @classmethod
    def for_period(cls, g: int, period: TimePeriod) -> "XZ3SFC":
        key = (g, TimePeriod.parse(period))
        if key not in cls._cache:
            cls._cache[key] = cls(g, period)
        return cls._cache[key]

    def index(
        self, xmin, ymin, tmin, xmax, ymax, tmax, lenient: bool = False
    ) -> np.ndarray:
        return self.index_boxes([xmin, ymin, tmin], [xmax, ymax, tmax], lenient)

    def ranges(
        self,
        queries: Sequence[Tuple[float, float, float, float, float, float]],
        max_ranges: Optional[int] = None,
    ) -> List[IndexRange]:
        windows = [((q[0], q[1], q[2]), (q[3], q[4], q[5])) for q in queries]
        return self.ranges_boxes(windows, max_ranges)
