"""Leaflet map export for notebooks (the geomesa-jupyter analog).

Reference: geomesa-jupyter-leaflet Leaflet.scala — a small DSL emitting
leaflet JS for in-notebook map display. Here: query results / density grids
-> a self-contained HTML document (CDN leaflet) or an IPython-displayable
object. Zero new dependencies.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

_PAGE = """<!DOCTYPE html>
<html><head>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>#map{{height:{height}px}}</style>
</head><body><div id="map"></div><script>
var map = L.map('map').setView([{lat}, {lon}], {zoom});
L.tileLayer('https://tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
            {{maxZoom: 19}}).addTo(map);
{layers}
</script></body></html>
"""


def _points_layer(result, color: str, limit: int) -> str:
    xs = result.columns.get(result.ft.default_geometry.name + "__x")
    ys = result.columns.get(result.ft.default_geometry.name + "__y")
    pts = [
        [float(ys[i]), float(xs[i])]
        for i in range(min(len(result), limit))
    ]
    return (
        f"var pts = {json.dumps(pts)};\n"
        f"pts.forEach(function(p) {{ L.circleMarker(p, "
        f"{{radius: 3, color: {color!r}}}).addTo(map); }});"
    )


def _density_layer(grid, envelope, opacity: float = 0.6) -> str:
    import numpy as np

    g = np.asarray(grid, dtype=float)
    mx = g.max() or 1.0
    xmin, ymin, xmax, ymax = envelope
    h, w = g.shape
    dx = (xmax - xmin) / w
    dy = (ymax - ymin) / h
    rects = []
    for r in range(h):
        for c in range(w):
            if g[r, c] > 0:
                rects.append(
                    [
                        [ymin + r * dy, xmin + c * dx],
                        [ymin + (r + 1) * dy, xmin + (c + 1) * dx],
                        round(float(g[r, c] / mx), 4),
                    ]
                )
    return (
        f"var cells = {json.dumps(rects)};\n"
        "cells.forEach(function(c) { L.rectangle([c[0], c[1]], "
        f"{{stroke: false, fillColor: 'red', fillOpacity: c[2] * {opacity}}}"
        ").addTo(map); });"
    )


def render_map(
    result=None,
    density: Optional[tuple] = None,  # (grid, envelope)
    center: Optional[tuple] = None,
    zoom: int = 3,
    height: int = 500,
    color: str = "#3388ff",
    max_points: int = 5000,
) -> str:
    """Self-contained HTML for a query result and/or density overlay."""
    layers: List[str] = []
    lat, lon = (center or (20.0, 0.0))
    if result is not None and len(result):
        layers.append(_points_layer(result, color, max_points))
        geom = result.ft.default_geometry.name
        lat = float(result.columns[geom + "__y"].mean())
        lon = float(result.columns[geom + "__x"].mean())
    if density is not None:
        layers.append(_density_layer(*density))
        if result is None or not len(result):
            env = density[1]
            lat = (env[1] + env[3]) / 2
            lon = (env[0] + env[2]) / 2
    return _PAGE.format(
        height=height, lat=lat, lon=lon, zoom=zoom, layers="\n".join(layers)
    )


class LeafletMap:
    """IPython-friendly wrapper: displays inline in a notebook."""

    def __init__(self, html: str):
        self.html = html

    def _repr_html_(self) -> str:
        return self.html.replace("#map{height", "#map{min-height")

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.html)
