"""Pluggable worker launchers: the process-lifecycle SPI of the fleet.

``FleetSupervisor`` (parallel/fleet.py) used to hard-code
``subprocess.Popen`` + a shared-filesystem portfile handshake, which
bound the whole fleet to one box. This module inverts that: every
process-lifecycle action — the first launch, the restart ladder after a
death, a standby takeover's adoption, the chaos harness's hard kill —
routes through one ``WorkerLauncher``, selected by the
``geomesa.fleet.launcher`` knob.

The CONTRACT is the endpoint handshake, not the portfile:

* ``launch(i)`` starts worker ``i`` by whatever means the launcher
  knows and returns a :class:`WorkerHandle` whose ``addr`` is a
  dialable ``(host, port)`` endpoint, within the spawn timeout. How the
  endpoint travels back is the launcher's private business — the local
  launcher polls the worker's atomically-published portfile, the ssh
  launcher reads the worker's ``ENDPOINT host:port`` announcement from
  the remote stdout (``--announce stdout``). A launch that cannot
  produce a live endpoint raises the crisp :class:`WorkerLaunchFailed`
  (an OSError: the supervisor's restart ladder classifies it as
  transient and backs off).
* ``adopt(i)`` attaches to a worker an earlier (dead) coordinator left
  behind: it reads the coordinator-side endpoint record every launch
  publishes under ``<base>/w<i>.endpoint``, probes it with a raw ping,
  and returns a handle WITHOUT starting anything — takeover must never
  double-spawn over a healthy worker's partition roots.
* ``poll(handle)`` answers "is this process observably dead?" from the
  launcher's local evidence (a reaped child, a dead pid). A remote
  worker whose transport is gone but whose death cannot be observed
  locally answers False — the heartbeat machine owns that verdict.
* ``kill(handle)`` / ``shutdown(handle)`` are the hard and graceful
  teardown levers.

Every launch runs under the ``fleet.launch`` fault point with a
``fleet.launch`` span and the handshake bounded by
``geomesa.fleet.spawn.timeout`` — the standing invariant: a new process
boundary is injectable, attributable, and deadline-bounded.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from geomesa_tpu.stream.netlog import recv_frame, request_envelope, send_frame
from geomesa_tpu.utils import deadline, faults, trace
from geomesa_tpu.utils.audit import robustness_metrics


class WorkerLaunchFailed(OSError):
    """Crisp launch failure: the worker process could not be started,
    exited before the handshake, or never announced a live endpoint
    inside ``geomesa.fleet.spawn.timeout``. Deliberately an OSError so
    the supervisor's restart ladder (``RetryPolicy`` over
    ``(OSError, TimeoutError)``) treats it exactly like any other
    transient infrastructure failure: bounded backoff, then OUT."""


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _repo_pythonpath() -> str:
    import geomesa_tpu

    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.abspath(geomesa_tpu.__file__))
    )
    existing = os.environ.get("PYTHONPATH", "")
    return pkg_parent + (os.pathsep + existing if existing else "")


def _worker_env(i: int) -> dict:
    """The environment every launched worker runs under (shared by the
    launchers so a loopback ssh template behaves like a local spawn)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo_pythonpath()
    # workers are host-scan processes: they must not race the
    # coordinator for an accelerator unless explicitly told to
    env.setdefault("JAX_PLATFORMS", "cpu")
    if env.get("JAX_PLATFORMS") == "cpu":
        # a cpu-pinned worker must not claim a remote accelerator
        # session at interpreter startup either (the force_cpu_platform
        # recipe, parallel/mesh.py — the claim can block for minutes
        # and serializes spawns)
        env["PALLAS_AXON_POOL_IPS"] = ""
    env["GEOMESA_FLEET_WORKER_ID"] = str(i)
    return env


def probe_endpoint(addr: Tuple[str, int]) -> Optional[int]:
    """Raw ping against a candidate endpoint: the serving worker's pid
    on success, None for anything dead/foreign (bounded at 1s —
    adoption probes must not serialize a takeover on a wedged corpse)."""
    try:
        s = socket.create_connection(addr, timeout=1.0)
    except OSError:
        return None
    try:
        s.settimeout(1.0)
        send_frame(s, json.dumps(request_envelope("ping", frames=0)).encode())
        resp = json.loads(recv_frame(s).decode())
        for _ in range(int(resp.get("frames", 0))):
            recv_frame(s)
        if resp.get("ok") != 1:
            return None
        return int(resp.get("pid") or 0) or None
    except (OSError, ValueError):
        return None
    finally:
        s.close()


@dataclass
class WorkerHandle:
    """One launched-or-adopted worker process as a launcher sees it.
    ``proc`` is the local child Popen when the launcher owns one (the
    local spawn, or the ssh CLIENT process); ``pid`` is the worker's
    pid as reported over the handshake — for a remote worker that pid
    lives on another host (``remote=True``) and must never be signalled
    locally."""

    worker_id: int
    addr: Tuple[str, int]
    pid: Optional[int] = None
    proc: Optional[subprocess.Popen] = None
    adopted: bool = False
    remote: bool = False
    launcher: str = "local"
    handshake_ms: float = 0.0


class WorkerLauncher:
    """The SPI. Subclasses implement ``_start``; ``launch`` wraps it in
    the fault point + span + deadline pairing and publishes the
    endpoint record adoption reads back."""

    kind = "abstract"

    def __init__(self, base_dir: str, worker_root: Callable[[int], str],
                 auths=None):
        self.base_dir = base_dir
        self.worker_root = worker_root
        self.auths = auths

    # -- the handshake contract ----------------------------------------------

    def endpoint_path(self, i: int) -> str:
        """Coordinator-side endpoint record: the generalized handshake
        artifact ``adopt`` trusts (after a probe). The portfile under
        the same directory is the LOCAL launcher's private mechanism."""
        return os.path.join(self.base_dir, f"w{i}.endpoint")

    def _publish_endpoint(self, i: int, addr: Tuple[str, int]) -> None:
        tmp = self.endpoint_path(i) + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(f"{addr[0]}:{addr[1]}\n")
        os.replace(tmp, self.endpoint_path(i))

    def _read_endpoint(self, i: int) -> Optional[Tuple[str, int]]:
        try:
            text = open(self.endpoint_path(i)).read().strip()
        except OSError:
            return None
        if not text:
            return None
        host, _, port = text.partition(":")
        try:
            return (host, int(port))
        except ValueError:
            return None

    # -- SPI -----------------------------------------------------------------

    def launch(self, i: int, timeout_s: float,
               stop: Optional[Callable[[], bool]] = None) -> WorkerHandle:
        """Start worker ``i`` and complete the endpoint handshake within
        ``timeout_s``. Raises ``WorkerLaunchFailed`` on any failure to
        produce a live endpoint, ``RuntimeError("supervisor stopping")``
        when ``stop()`` turned true mid-handshake."""
        t0 = time.monotonic()
        with trace.span("fleet.launch", worker=i, launcher=self.kind):
            # a launch inside a bounded repair (or a bounded takeover)
            # must not outlive the caller's budget: cooperative check
            # first, then the injectable boundary itself
            deadline.check("fleet.launch")
            faults.fault_point("fleet.launch")
            try:
                handle = self._start(i, timeout_s, stop or (lambda: False))
            except (WorkerLaunchFailed, RuntimeError):
                robustness_metrics().inc("fleet.launch.failed")
                raise
            except (OSError, ValueError, subprocess.SubprocessError) as e:
                robustness_metrics().inc("fleet.launch.failed")
                raise WorkerLaunchFailed(
                    f"fleet worker {i} launch via {self.kind!r} failed: {e}"
                ) from e
            handle.launcher = self.kind
            handle.handshake_ms = (time.monotonic() - t0) * 1000.0
            self._publish_endpoint(i, handle.addr)
            robustness_metrics().inc("fleet.worker.launched")
            trace.event(
                "fleet.worker.launched", worker=i, launcher=self.kind,
                handshake_ms=round(handle.handshake_ms, 1),
            )
            return handle

    def _start(self, i: int, timeout_s: float,
               stop: Callable[[], bool]) -> WorkerHandle:
        raise NotImplementedError

    def adopt(self, i: int) -> Optional[WorkerHandle]:
        """Attach to an already-running worker — one a dead coordinator
        left behind — via the published endpoint record + a raw probe.
        None when there is nothing live to adopt."""
        addr = self._read_endpoint(i)
        if addr is None:
            return None
        pid = probe_endpoint(addr)
        if pid is None:
            return None
        return WorkerHandle(
            worker_id=i, addr=addr, pid=pid, proc=None, adopted=True,
            remote=self._pid_is_remote(), launcher=self.kind,
        )

    def _pid_is_remote(self) -> bool:
        return False

    def poll(self, handle: WorkerHandle) -> bool:
        """True when the process is OBSERVABLY dead from here (reaped
        child / dead local pid). A remote worker with no local evidence
        answers False — missed heartbeats carry that verdict."""
        if handle.proc is not None:
            return handle.proc.poll() is not None
        if handle.pid is not None and not handle.remote:
            return not _pid_alive(handle.pid)
        return False

    def kill(self, handle: WorkerHandle, wait_s: float = 5.0) -> None:
        """Hard-kill (SIGKILL) — the chaos harness's and the respawn
        ladder's lever. Waits up to ``wait_s`` for the death to be
        locally observable so a respawn never races its predecessor."""
        if handle.proc is not None and handle.proc.poll() is None:
            handle.proc.kill()
            try:
                handle.proc.wait(timeout=wait_s)
            except subprocess.TimeoutExpired:
                pass
            return
        if handle.pid is None or handle.remote:
            return
        if _pid_alive(handle.pid):
            try:
                os.kill(handle.pid, signal.SIGKILL)
            except OSError:
                return
            t_end = time.monotonic() + wait_s
            while time.monotonic() < t_end and _pid_alive(handle.pid):
                time.sleep(0.02)

    def shutdown(self, handle: WorkerHandle, timeout_s: float = 2.0) -> None:
        """Graceful teardown: SIGTERM, bounded wait, then SIGKILL."""
        if handle.proc is not None:
            if handle.proc.poll() is not None:
                return
            handle.proc.terminate()
            try:
                handle.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                handle.proc.kill()
                try:
                    handle.proc.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    pass
            return
        if handle.pid is None or handle.remote:
            return
        try:
            os.kill(handle.pid, signal.SIGTERM)
        except OSError:
            return
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end and _pid_alive(handle.pid):
            time.sleep(0.05)
        if _pid_alive(handle.pid):
            try:
                os.kill(handle.pid, signal.SIGKILL)
            except OSError:
                pass


class LocalSpawnLauncher(WorkerLauncher):
    """Today's behavior, now behind the SPI: ``subprocess.Popen`` of
    ``python -m geomesa_tpu.parallel.fleet --worker`` with the bound
    port published through an atomically-replaced portfile the launcher
    polls. The portfile is PRIVATE to this launcher; adoption still
    falls back to it so roots written before the endpoint record
    existed stay adoptable."""

    kind = "local"

    def portfile(self, i: int) -> str:
        return os.path.join(self.base_dir, f"w{i}.port")

    def _worker_cmd(self, i: int) -> list:
        cmd = [
            sys.executable,
            "-m",
            "geomesa_tpu.parallel.fleet",
            "--worker",
            "--id",
            str(i),
            "--root",
            self.worker_root(i),
            "--portfile",
            self.portfile(i),
        ]
        # list-shaped auths travel to the worker stores (visibility rows
        # must filter identically on both sides of the wire); provider
        # OBJECTS cannot cross a process boundary — workers then run
        # auth-less and visibility-bearing scans under-serve (documented)
        auths = self.auths
        if isinstance(auths, str):
            auths = [auths]
        if isinstance(auths, (list, tuple)) and all(
            isinstance(a, str) for a in auths
        ) and auths:
            cmd += ["--auths", ",".join(auths)]
        return cmd

    def _start(self, i: int, timeout_s: float,
               stop: Callable[[], bool]) -> WorkerHandle:
        portfile = self.portfile(i)
        try:
            os.remove(portfile)
        except FileNotFoundError:
            pass
        log = open(os.path.join(self.base_dir, f"w{i}.log"), "ab")
        try:
            proc = subprocess.Popen(
                self._worker_cmd(i), env=_worker_env(i), stdout=log,
                stderr=log,
            )
        finally:
            log.close()
        t_end = time.monotonic() + timeout_s
        addr: Optional[Tuple[str, int]] = None
        while time.monotonic() < t_end:
            if stop():
                # the supervisor's stop() is waiting on this repair:
                # abort promptly instead of making close()/atexit wait
                # out the handshake timeout
                proc.kill()
                raise RuntimeError("supervisor stopping")
            if proc.poll() is not None:
                raise WorkerLaunchFailed(
                    f"fleet worker {i} exited rc={proc.returncode} "
                    "during spawn"
                )
            try:
                text = open(portfile).read().strip()
            except FileNotFoundError:
                time.sleep(0.02)
                continue
            if text:
                host, _, port = text.partition(":")
                addr = (host, int(port))
                break
            time.sleep(0.02)
        if addr is None:
            proc.kill()
            raise WorkerLaunchFailed(
                f"fleet worker {i} never published its port"
            )
        return WorkerHandle(worker_id=i, addr=addr, pid=proc.pid, proc=proc)

    def adopt(self, i: int) -> Optional[WorkerHandle]:
        handle = super().adopt(i)
        if handle is not None:
            return handle
        # pre-endpoint-record roots: the worker-published portfile is
        # still a valid (local-only) handshake artifact
        try:
            text = open(self.portfile(i)).read().strip()
        except OSError:
            return None
        if not text:
            return None
        host, _, port = text.partition(":")
        try:
            addr = (host, int(port))
        except ValueError:
            return None
        pid = probe_endpoint(addr)
        if pid is None:
            return None
        return WorkerHandle(
            worker_id=i, addr=addr, pid=pid, proc=None, adopted=True,
            launcher=self.kind,
        )


class SshLauncher(WorkerLauncher):
    """A command-template launcher: ``geomesa.fleet.ssh.command`` is a
    shell template with ``{python}``/``{id}``/``{root}``/``{host}``
    placeholders, rendered per worker and run as the launch command
    (typically ``ssh <host> ...``; the tests drive it with a local
    loopback template — no ssh binary — which exercises the identical
    template + stdout-handshake path). The launched worker must run
    with ``--announce stdout`` so its ``ENDPOINT host:port`` line
    travels back over the command's stdout: no shared filesystem in the
    contract.

    Lifecycle caveats, by design: ``poll``/``kill``/``shutdown`` act on
    the LOCAL command process (for real ssh, killing the client tears
    the session; ``ssh -tt`` propagates the hangup to the remote
    worker), and an adopted remote worker's pid is never signalled
    locally — a takeover that must retire one goes through the worker's
    own drain RPC or the remote host's supervisor. The rendered command
    runs ``shell=True`` in its OWN session, and every local signal goes
    to the process GROUP: signalling only the shell would reap it while
    orphaning whatever it spawned (the loopback template's worker, a
    wrapper script's ssh client) — the leak that poisons every test and
    bench that runs after a fleet teardown."""

    kind = "ssh"

    def __init__(self, base_dir: str, worker_root: Callable[[int], str],
                 auths=None, command_template: Optional[str] = None):
        super().__init__(base_dir, worker_root, auths=auths)
        if command_template is None:
            from geomesa_tpu.utils.config import FLEET_SSH_COMMAND

            command_template = FLEET_SSH_COMMAND.get()
        if not command_template:
            raise ValueError(
                "geomesa.fleet.launcher=ssh needs geomesa.fleet.ssh.command "
                "(a shell template with {python} {id} {root} {host} "
                "placeholders)"
            )
        self.command_template = str(command_template)

    def _pid_is_remote(self) -> bool:
        return True

    @staticmethod
    def _signal_command(proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(proc.pid, sig)
        except OSError:
            try:
                proc.send_signal(sig)
            except OSError:
                pass

    def kill(self, handle: WorkerHandle, wait_s: float = 5.0) -> None:
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return  # adopted remote pid: never signalled locally
        self._signal_command(proc, signal.SIGKILL)
        try:
            proc.wait(timeout=wait_s)
        except subprocess.TimeoutExpired:
            pass

    def shutdown(self, handle: WorkerHandle, timeout_s: float = 2.0) -> None:
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        self._signal_command(proc, signal.SIGTERM)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self._signal_command(proc, signal.SIGKILL)
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                pass

    def _render(self, i: int) -> str:
        return self.command_template.format(
            python=sys.executable,
            id=i,
            root=self.worker_root(i),
            host="127.0.0.1",
        )

    def _start(self, i: int, timeout_s: float,
               stop: Callable[[], bool]) -> WorkerHandle:
        cmd = self._render(i)
        log = open(os.path.join(self.base_dir, f"w{i}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, shell=True, env=_worker_env(i),
                stdout=subprocess.PIPE, stderr=log,
                start_new_session=True,
            )
        finally:
            log.close()
        assert proc.stdout is not None
        fd = proc.stdout.fileno()
        buf = b""
        t_end = time.monotonic() + timeout_s
        addr: Optional[Tuple[str, int]] = None
        pid: Optional[int] = None
        while time.monotonic() < t_end and addr is None:
            if stop():
                self._signal_command(proc, signal.SIGKILL)
                raise RuntimeError("supervisor stopping")
            ready, _, _ = select.select([fd], [], [], 0.05)
            if not ready:
                if proc.poll() is not None:
                    raise WorkerLaunchFailed(
                        f"fleet worker {i} launch command exited "
                        f"rc={proc.returncode} before announcing an endpoint"
                    )
                continue
            data = os.read(fd, 4096)
            if not data:
                if proc.poll() is not None:
                    raise WorkerLaunchFailed(
                        f"fleet worker {i} launch command exited "
                        f"rc={proc.returncode} before announcing an endpoint"
                    )
                time.sleep(0.02)
                continue
            buf += data
            while b"\n" in buf and addr is None:
                line, _, buf = buf.partition(b"\n")
                parts = line.decode("utf-8", "replace").strip().split()
                # "ENDPOINT host:port [pid]" — the worker's stdout
                # announcement (--announce stdout, worker_main)
                if len(parts) >= 2 and parts[0] == "ENDPOINT":
                    host, _, port = parts[1].partition(":")
                    try:
                        addr = (host, int(port))
                    except ValueError:
                        self._signal_command(proc, signal.SIGKILL)
                        raise WorkerLaunchFailed(
                            f"fleet worker {i} announced a malformed "
                            f"endpoint {parts[1]!r}"
                        ) from None
                    if len(parts) >= 3 and parts[2].isdigit():
                        pid = int(parts[2])
        if addr is None:
            self._signal_command(proc, signal.SIGKILL)
            raise WorkerLaunchFailed(
                f"fleet worker {i} never announced its endpoint"
            )
        return WorkerHandle(
            worker_id=i, addr=addr, pid=pid, proc=proc, remote=True,
        )


def make_launcher(base_dir: str, worker_root: Callable[[int], str],
                  auths=None, kind: Optional[str] = None) -> WorkerLauncher:
    """The ``geomesa.fleet.launcher`` knob -> a launcher instance."""
    if kind is None:
        from geomesa_tpu.utils.config import FLEET_LAUNCHER

        kind = (FLEET_LAUNCHER.get() or "local").strip().lower()
    if kind == "local":
        return LocalSpawnLauncher(base_dir, worker_root, auths=auths)
    if kind == "ssh":
        return SshLauncher(base_dir, worker_root, auths=auths)
    raise ValueError(
        f"unknown geomesa.fleet.launcher {kind!r} (known: local, ssh)"
    )
