"""Device mesh construction and columnar sharding helpers.

Blocks are sharded along their row axis (the analog of tablet splits,
api/GeoMesaFeatureIndex.scala:116 getSplits); query descriptors are
replicated. Multi-host meshes ride DCN automatically through jax's global
device set — the layout code here is identical single-chip and pod-scale.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "shards"


def default_mesh(devices: Optional[Sequence] = None, axis: str = DATA_AXIS) -> Mesh:
    """1D mesh over all (or the given) devices; rows shard over ``axis``."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def pad_to_multiple(arr: np.ndarray, multiple: int, fill) -> np.ndarray:
    """Pad axis 0 to a multiple so rows divide evenly across shards."""
    n = arr.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return arr
    pad_width = [(0, rem)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def shard_array(mesh: Mesh, arr: np.ndarray, axis: str = DATA_AXIS):
    """Place a host array on the mesh, sharded along axis 0."""
    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def replicate(mesh: Mesh, arr: np.ndarray):
    """Place a host array on the mesh fully replicated (query descriptors)."""
    return jax.device_put(arr, NamedSharding(mesh, P()))
