"""Device mesh construction and columnar sharding helpers.

Blocks are sharded along their row axis (the analog of tablet splits,
api/GeoMesaFeatureIndex.scala:116 getSplits); query descriptors are
replicated. Multi-host meshes ride DCN automatically through jax's global
device set — the layout code here is identical single-chip and pod-scale.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomesa_tpu.utils import deadline, faults, trace
from geomesa_tpu.utils.devstats import count_h2d, instrumented_jit

DATA_AXIS = "shards"

# per-device-set dispatch gates (see dispatch_gate): one entry per
# distinct multi-device set, shared across every Mesh built over it
_DISPATCH_GATES: Dict[tuple, threading.RLock] = {}
_DISPATCH_GATES_LOCK = threading.Lock()


def dispatch_gate(mesh) -> Optional[threading.RLock]:
    """The per-mesh dispatch gate: at most ONE collective-bearing XLA
    program in flight per device set.

    XLA's collective rendezvous assumes programs reach every
    participating device in one global order; two host threads each
    launching a program with collectives (the all-gather of
    ``executor._gathered``, a cross-shard ``jnp.sum`` reduction) onto
    the SAME multi-device mesh can interleave their launches and
    deadlock the rendezvous — the hazard PR 9's concurrency tests
    surfaced with concurrent SOLO queries. The fence: callers hold this
    gate from launch until the program's outputs are READY, so no
    collective of one program can still be pending when the next
    launches. Keyed by the underlying device set (not the Mesh object),
    so every Mesh built over the same chips shares one gate; re-entrant
    so a gated kernel may compose gated helpers.

    Returns None — no gating — for single-device meshes (nothing to
    rendezvous) and under ``GEOMESA_SPMD_GATE=0`` (A/B escape hatch;
    shipping code must treat the gate as always on). Collective-free
    kernels (the shard_map shard-extract and stacked-mask editions,
    whose bodies contain no cross-shard communication) never consult
    the gate at all — that layout is the other half of the
    rendezvous-safety contract."""
    import os

    if mesh is None or getattr(mesh, "devices", np.empty(0)).size <= 1:
        return None
    if os.environ.get("GEOMESA_SPMD_GATE", "1") == "0":
        return None
    key = tuple(
        (getattr(d, "platform", "?"), getattr(d, "id", id(d)))
        for d in mesh.devices.flat
    )
    with _DISPATCH_GATES_LOCK:
        gate = _DISPATCH_GATES.get(key)
        if gate is None:
            gate = _DISPATCH_GATES[key] = threading.RLock()
    return gate


def gated(fn, mesh):
    """Wrap a jitted multi-device execution in the mesh's dispatch gate
    (see ``dispatch_gate``): the call holds the gate until its outputs
    are READY, so no collective of this program can still be pending
    when another thread launches the next one. Single-device meshes
    (and ``GEOMESA_SPMD_GATE=0``) return ``fn`` unchanged — zero
    overhead exactly where there is nothing to rendezvous."""
    gate = dispatch_gate(mesh)
    if gate is None:
        return fn

    def call(*args, **kwargs):
        with gate:
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            return out

    call.__name__ = f"mesh_gated[{getattr(fn, '__name__', 'fn')}]"
    for shared in ("_jitted", "_devstats"):
        if hasattr(fn, shared):
            setattr(call, shared, getattr(fn, shared))
    return call


def force_cpu_platform(min_devices: int = 0):
    """Pin jax to the cpu platform and return its devices, never touching
    the default (possibly remote-TPU) backend.

    The axon site hook registers a remote platform at interpreter startup
    and bakes ``jax_platforms="axon,cpu"`` into jax's CONFIG, so the env
    var alone does not stop ``jax.devices()`` from initializing (and
    potentially hanging on) the tunnel. Both the env var and the config
    must be forced before any backend initializes. If ``min_devices`` > 1
    and the cpu backend is not yet initialized, the
    ``xla_force_host_platform_device_count`` flag is added so a virtual
    multi-device mesh exists even when the caller's env forgot it.
    """
    import os
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    # children of a cpu-pinned process must not claim a remote session either
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    if min_devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={min_devices}"
            ).strip()
        elif int(m.group(1)) < min_devices:
            os.environ["XLA_FLAGS"] = flags.replace(
                m.group(0), f"--xla_force_host_platform_device_count={min_devices}"
            )
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backends already initialized; explicit "cpu" lookup below
    devices = jax.devices("cpu")
    if min_devices and len(devices) < min_devices:
        raise RuntimeError(
            f"cpu backend has {len(devices)} device(s), need {min_devices}; "
            "set XLA_FLAGS=--xla_force_host_platform_device_count before jax "
            "initializes"
        )
    return devices


def shard_map_fn(f, mesh: Mesh, in_specs, out_specs, check: bool = True):
    """shard_map across jax versions; check=False disables the replication/
    vma checker (required when the per-shard body is a pallas_call, whose
    out_shape carries no vma annotation)."""
    try:
        from jax import shard_map as sm
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as sm
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if not check:
        try:
            return sm(f, check_vma=False, **kwargs)
        except TypeError:  # pragma: no cover - pre-vma jax uses check_rep
            return sm(f, check_rep=False, **kwargs)
    return sm(f, **kwargs)


def default_mesh(devices: Optional[Sequence] = None, axis: str = DATA_AXIS) -> Mesh:
    """1D mesh over all (or the given) devices; rows shard over ``axis``."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def multihost_mesh(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    axis: str = DATA_AXIS,
) -> Mesh:
    """Mesh spanning every chip of every host (the multi-host DCN path).

    The reference scales batch compute by adding Spark executors over the
    database's RPC fabric (AccumuloSpatialRDDProvider); here the fabric is
    jax's distributed runtime: each host calls this with the same
    coordinator address, ``jax.distributed.initialize`` wires DCN, and
    ``jax.devices()`` becomes the GLOBAL device set. Collectives inserted
    by shard_map/pjit ride ICI within a host and DCN across hosts — the
    executor's scan/merge code is unchanged at any scale.

    With no arguments this is a no-op wrapper around the local device set
    (single-controller dev mode and tests).
    """
    if coordinator is not None:
        try:
            # the CPU backend only runs multi-process collectives over
            # gloo, and the default is "none" — without this, the first
            # cross-process psum dies with "Multiprocess computations
            # aren't implemented on the CPU backend". Harmless on real
            # TPU/GPU pods (the flag only configures the cpu backend)
            # and must be set BEFORE any backend initializes.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - older jax without the flag
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    devices = jax.devices()
    # hosts first: keeps each host's chips contiguous along the data axis so
    # block shards stay host-local and cross-host traffic is merge-only
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    return Mesh(np.asarray(devices), (axis,))


def pad_to_multiple(arr: np.ndarray, multiple: int, fill) -> np.ndarray:
    """Pad axis 0 to a multiple so rows divide evenly across shards."""
    n = arr.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return arr
    pad_width = [(0, rem)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def shard_array(mesh: Mesh, arr: np.ndarray, axis: str = DATA_AXIS):
    """Place a host array on the mesh, sharded along axis 0.

    ``device.dispatch`` fault point: every H2D placement (mirror uploads
    and query descriptors) passes here or through ``replicate``, so an
    injected dispatch fault exercises the executor's device->host
    degradation exactly where a dead tunnel would surface. The span of
    the same name is the tracing half of that contract: every H2D
    boundary crossing lands on the owning query's span tree."""
    with trace.span("device.dispatch", bytes=int(getattr(arr, "nbytes", 0))):
        deadline.check("device.dispatch")
        faults.fault_point("device.dispatch")
        out = jax.device_put(arr, NamedSharding(mesh, P(axis)))
        # counted AFTER the put: a faulted/failed dispatch moved nothing,
        # and the degradation path must not inflate the link counters
        count_h2d(int(getattr(arr, "nbytes", 0)))
        return out


def replicate(mesh: Mesh, arr: np.ndarray):
    """Place a host array on the mesh fully replicated (query descriptors)."""
    with trace.span("device.dispatch", bytes=int(getattr(arr, "nbytes", 0))):
        deadline.check("device.dispatch")
        faults.fault_point("device.dispatch")
        out = jax.device_put(arr, NamedSharding(mesh, P()))
        count_h2d(int(getattr(arr, "nbytes", 0)))
        return out


_LINK_LATENCY_MS: Optional[float] = None


def link_latency_ms() -> float:
    """Measured host<->device round-trip latency (ms), cached per process.

    The per-query cost floor of any device dispatch. A PCIe-attached chip
    measures well under 1 ms; the axon remote-TPU tunnel measured ~70-95 ms
    per execution (round-3 silicon session). Cost-based executor choices
    (device kNN/density autos) consult this so a high-latency link prefers
    host kernels while a local accelerator keeps the device paths — the
    same per-deployment cost asymmetry the reference handles by moving
    compute to the data (SURVEY.md section 2.6). CPU backend: 0 (device
    compute IS host compute). GEOMESA_LINK_LATENCY_MS overrides (tests,
    known deployments)."""
    global _LINK_LATENCY_MS
    import os

    env = os.environ.get("GEOMESA_LINK_LATENCY_MS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if _LINK_LATENCY_MS is None:
        if jax.default_backend() == "cpu":
            _LINK_LATENCY_MS = 0.0
        else:
            import time
            import numpy as _np

            f = instrumented_jit("link_probe", lambda x: x + 1)
            x = jax.device_put(_np.zeros(8, _np.float32))
            _np.asarray(f(x))  # compile + first transfer
            samples = []
            for _ in range(5):
                t0 = time.perf_counter()
                _np.asarray(f(x))
                samples.append((time.perf_counter() - t0) * 1000.0)
            # MIN, not median: the cost model wants the link's FLOOR, and
            # host-side contention only ever inflates samples (a loaded
            # box once measured >10 ms on a 0.2 ms tunnel and parked the
            # density auto on the host path for the whole process)
            _LINK_LATENCY_MS = float(min(samples))
    return _LINK_LATENCY_MS


def device_auto_declines(env_var: str, link_cap_ms: float = 10.0) -> bool:
    """The shared auto-mode cost gate for scalar/aggregate device
    push-downs (count/stats/density): True when the path should decline
    to the host — forced off ("0"), or in auto mode on the CPU backend
    (where "device" compute IS host compute) or over a high-latency
    link (the per-execution floor loses to the host seek's sub-ms
    answer). An explicit "1" always passes."""
    import os

    import jax

    env = os.environ.get(env_var, "auto")
    if env == "0":
        return True
    if env == "1":
        return False
    if jax.default_backend() == "cpu":
        return True
    return link_latency_ms() > link_cap_ms


def device_tripped(executor, env_var: str) -> bool:
    """True when a device path already failed this session AND the
    operator has not forced THIS path on (env_var != "1"): auto-mode
    queries stick to the host after one tunnel/backend failure instead
    of paying the failure latency per query; an explicit =1 keeps
    retrying. One home for the gate check the device kNN and density
    autos share."""
    import os

    if os.environ.get(env_var, "auto") == "1":
        return False
    return bool(getattr(executor, "_device_tripped", False))


def trip_device(executor, env_var: str, tag: str, exc: BaseException) -> None:
    """Record a device-path failure: one stderr line, and set the
    executor's session trip flag — UNLESS the operator forced this path
    on (a deterministic kernel-specific failure under a forced =1 must
    not poison the OTHER auto-mode device paths on a healthy tunnel)."""
    import os
    import sys

    if os.environ.get(env_var, "auto") != "1":
        executor._device_tripped = True
    sys.stderr.write(
        f"[{tag}] device path failed ({type(exc).__name__}); "
        "host path answers\n"
    )
