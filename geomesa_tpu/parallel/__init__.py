"""SPMD execution over a TPU device mesh.

The reference parallelizes scans with client thread pools + server-side
iterators across tablet servers (SURVEY.md section 2.6); the TPU analog keeps
index tables as columnar shards laid out over a ``jax.sharding.Mesh`` and
broadcasts query descriptors, with partial hit masks merged by XLA collectives
(psum over the range axis, all_gather of per-shard counts).
"""

from geomesa_tpu.parallel.mesh import default_mesh, shard_array, pad_to_multiple
from geomesa_tpu.parallel.executor import TpuScanExecutor, DeviceIndex

# the shard fabric (parallel/shards.py) imports store.datastore, which
# imports this package — resolve lazily so either import order works
_SHARD_EXPORTS = (
    "ShardedDataStore",
    "ShardWorker",
    "PlacementMap",
    "ShardDied",
    "mesh_executor_factory",
)


def __getattr__(name):
    if name in _SHARD_EXPORTS:
        from geomesa_tpu.parallel import shards

        return getattr(shards, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
